"""Mixture-of-Experts decoder (Mixtral-shaped) — the second model family.

Reuses the Llama attention stack; the MLP becomes a top-k token-choice
router over E experts. Two execution paths, both TPU-first:

- Dense (default, single chip / small E): every expert evaluated per
  token, combined by router weight — static shapes, no gather/scatter,
  XLA tiles everything onto the MXU. The right trade below ~16 experts.
- Expert-parallel (``forward(..., mesh=mesh, ep=True)``): GShard-style
  dispatch over the dedicated ``ep`` mesh axis. Tokens are bucketed per
  expert with a capacity factor (static shapes — overflow assignments
  drop, as in Switch/GShard), exchanged via ``lax.all_to_all`` over ICI,
  processed by each device's expert shard, and returned by the inverse
  all_to_all. This is the path that scales past the dense trade, and
  the load-balance auxiliary loss keeps the router from collapsing onto
  few experts (which would amplify capacity drops).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:                       # moved to the top level in newer jax
    from jax import shard_map as _shard_map
except ImportError:        # jax <= 0.4.x keeps it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map



def _pcast_varying(x, axes):
    # lax.pcast's varying-type marking exists only in newer jax; the
    # 0.4.x shard_map has no varying types, so identity is exact there.
    pcast = getattr(lax, "pcast", None)
    return pcast(x, axes, to="varying") if pcast is not None else x


def _axis_size(name):
    # lax.axis_size is newer-jax; psum(1, axis) is the classic idiom it
    # replaced and constant-folds to the same static size under shard_map.
    size = getattr(lax, "axis_size", None)
    return size(name) if size is not None else lax.psum(1, name)

from grove_tpu.models import llama
from grove_tpu.models.llama import LlamaConfig, _attn_out, _qkv
from grove_tpu.ops.attention import causal_attention
from grove_tpu.ops.norms import rms_norm
from grove_tpu.ops.rope import rope_table
from grove_tpu.parallel.mesh import (
    AXIS_DP, AXIS_EP, AXIS_PP, AXIS_SP, AXIS_TP,
)
from grove_tpu.parallel.sharding import param_pspecs

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoeConfig(LlamaConfig):
    n_experts: int = 8
    experts_per_token: int = 2


MOE_CONFIGS: dict[str, MoeConfig] = {
    "moe-test-tiny": MoeConfig(vocab_size=256, d_model=64, n_layers=2,
                               n_heads=8, n_kv_heads=4, d_ff=96, head_dim=8,
                               max_seq_len=128, n_experts=4,
                               experts_per_token=2),
    # Mixtral-8x7B-shaped (docs/perf projections)
    "mixtral-8x7b": MoeConfig(vocab_size=32000, d_model=4096, n_layers=32,
                              n_heads=32, n_kv_heads=8, d_ff=14336,
                              head_dim=128, max_seq_len=8192, n_experts=8,
                              experts_per_token=2),
}


def init_params(cfg: MoeConfig, key: jax.Array) -> Params:
    """Llama attention/embed params plus router + stacked experts (the
    dense MLP is never allocated — for real configs it would be a
    multi-GB throwaway)."""
    base = llama.init_params(cfg, key, include_mlp=False)
    L, d, ff, E = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.n_experts
    ks = jax.random.split(jax.random.fold_in(key, 17), 4)
    layers = base["layers"]
    layers["router"] = llama.dense_init(cfg, ks[0], (L, d, E), d)
    layers["we_gate"] = llama.dense_init(cfg, ks[1], (L, E, d, ff), d)
    layers["we_up"] = llama.dense_init(cfg, ks[2], (L, E, d, ff), d)
    layers["we_down"] = llama.dense_init(cfg, ks[3], (L, E, ff, d), ff)
    return base


def _moe_block(cfg: MoeConfig, x, lp):
    """Top-k routed expert MLP with residual. x: [b, s, d]."""
    hm = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,de->bse", hm, lp["router"],
                        preferred_element_type=jnp.float32)
    k = cfg.experts_per_token
    top_vals, top_idx = lax.top_k(logits, k)                  # [b, s, k]
    gate_w = jax.nn.softmax(top_vals, axis=-1)                # [b, s, k]
    # Dense weight mask over experts: [b, s, E]
    one_hot = jax.nn.one_hot(top_idx, cfg.n_experts, dtype=gate_w.dtype)
    weights = jnp.einsum("bsk,bske->bse", gate_w, one_hot)
    # Evaluate all experts densely, combine by weight (static shapes).
    gate = jnp.einsum("bsd,edf->besf", hm, lp["we_gate"])
    up = jnp.einsum("bsd,edf->besf", hm, lp["we_up"])
    expert_out = jnp.einsum("besf,efd->besd", jax.nn.silu(gate) * up,
                            lp["we_down"])
    out = jnp.einsum("bse,besd->bsd", weights.astype(expert_out.dtype),
                     expert_out)
    return x + out.astype(x.dtype)


def router_load_balance_loss(router_logits: jnp.ndarray,
                             top_idx: jnp.ndarray, n_experts: int
                             ) -> jnp.ndarray:
    """Switch-Transformer auxiliary loss: E · Σ_e f_e · p_e, minimised at
    uniform routing. f_e = fraction of assignments to expert e; p_e =
    mean router probability. Keeps the router balanced so capacity drops
    stay rare on the expert-parallel path."""
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    p = probs.reshape(-1, n_experts).mean(axis=0)
    counts = jax.nn.one_hot(top_idx.reshape(-1), n_experts,
                            dtype=jnp.float32).mean(axis=0)
    return n_experts * jnp.sum(counts * p)


def _ep_moe_block(cfg: MoeConfig, x, lp, capacity_factor: float):
    """Expert-parallel routed MLP under shard_map (GShard dispatch).

    x: [bl, s, d] — this member's token shard. Experts are sharded over
    the ``ep`` axis (lp["we_*"]: [E/ep, d, ff] local slices, global
    expert e lives on member e // (E/ep)). Static capacity buckets make
    every shape compile-time constant; overflow assignments are dropped
    (their tokens keep the residual path only).
    """
    ep = _axis_size(AXIS_EP)
    E, k = cfg.n_experts, cfg.experts_per_token
    El = E // ep
    bl, s, d = x.shape
    n = bl * s
    capacity = max(1, int(math.ceil(n * k / E * capacity_factor)))

    hm = rms_norm(x, lp["mlp_norm"], cfg.norm_eps).reshape(n, d)
    logits = jnp.einsum("nd,de->ne", hm, lp["router"],
                        preferred_element_type=jnp.float32)
    top_vals, top_idx = lax.top_k(logits, k)               # [n, k]
    gate_w = jax.nn.softmax(top_vals, axis=-1)
    flat_e = top_idx.reshape(-1)                           # [n*k]
    flat_w = gate_w.reshape(-1).astype(hm.dtype)

    # Position of each assignment within its expert's bucket; beyond
    # capacity → slot index `capacity`, which scatters into the void.
    oh = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)
    pos = jnp.cumsum(oh, axis=0) - 1
    pos_in_e = jnp.take_along_axis(pos, flat_e[:, None], axis=1)[:, 0]
    slot = jnp.where(pos_in_e < capacity, pos_in_e, capacity)

    toks = jnp.repeat(hm, k, axis=0)                       # [n*k, d]
    buckets = jnp.zeros((E, capacity, d), hm.dtype)
    buckets = buckets.at[flat_e, slot].set(toks, mode="drop")

    # Dispatch: bucket for global expert j*El+e goes to ep member j.
    send = buckets.reshape(ep, El, capacity, d)
    recv = lax.all_to_all(send, AXIS_EP, split_axis=0, concat_axis=0)
    # recv[i, e] = peer i's bucket for my local expert e.
    expert_in = recv.transpose(1, 0, 2, 3).reshape(El, ep * capacity, d)
    g = jnp.einsum("ecd,edf->ecf", expert_in, lp["we_gate"])
    u = jnp.einsum("ecd,edf->ecf", expert_in, lp["we_up"])
    out = jnp.einsum("ecf,efd->ecd", jax.nn.silu(g) * u, lp["we_down"])
    # Return: inverse exchange restores [E, capacity, d] on each member.
    back = out.reshape(El, ep, capacity, d).transpose(1, 0, 2, 3)
    mine = lax.all_to_all(back, AXIS_EP, split_axis=0, concat_axis=0)
    mine = mine.reshape(E, capacity, d)

    # Gather per assignment; dropped slots read the zero pad row.
    padded = jnp.pad(mine, ((0, 0), (0, 1), (0, 0)))
    out_assign = padded[flat_e, slot] * flat_w[:, None]
    moe_out = out_assign.reshape(n, k, d).sum(axis=1)
    return (x + moe_out.reshape(bl, s, d).astype(x.dtype),
            router_load_balance_loss(logits, top_idx, E))


def _decoder_stack(cfg: MoeConfig, params, tokens, moe_fn, aux0):
    """The shared decoder skeleton (embed → [attention + moe] × L →
    norm → head). ONE copy for both execution paths — ``moe_fn(x, lp)``
    → (x, layer_aux) is the only difference between dense and
    expert-parallel, so the paths cannot drift apart."""
    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    x = params["tok_embed"][tokens].astype(cfg.dtype)

    def body(carry, lp):
        x, aux = carry
        q, k, v = _qkv(cfg, x, lp, cos, sin, positions)
        x = _attn_out(x, causal_attention(q, k, v), lp)
        x, layer_aux = moe_fn(x, lp)
        return (x, aux + layer_aux), None

    (x, aux), _ = lax.scan(body, (x, aux0), params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    return logits, aux / cfg.n_layers


def _ep_body(cfg: MoeConfig, capacity_factor: float, params, tokens):
    """shard_map body: tokens batch-sharded over (dp, ep), experts
    sharded over ep, attention token-local."""
    # The aux accumulator must carry the device-varying type from the
    # start (layer aux varies over dp/ep) or the scan carry types differ.
    # Shape (1,) rather than scalar: under grad, partial-eval saves it as
    # a residual with the all-axes residual spec on axis 0, which a
    # rank-0 value cannot carry (older shard_map rejects it outright).
    aux0 = _pcast_varying(jnp.zeros((1,), jnp.float32), (AXIS_DP, AXIS_EP))

    def moe_fn(x, lp):
        y, layer_aux = _ep_moe_block(cfg, x, lp, capacity_factor)
        return y, layer_aux[None]

    logits, aux = _decoder_stack(cfg, params, tokens, moe_fn, aux0)
    # Per-shard aux out (mapped over dp×ep, meaned by the caller): the
    # math is identical to an in-body pmean → replicated scalar, but a
    # mapped output transposes cleanly on every jax version — older
    # shard_map cannot type the replicated-scalar cotangent under grad.
    return logits, aux


def _collapse_to_dp_ep(spec: P) -> P:
    """Drop mesh axes other than dp/ep from a PartitionSpec (valid only
    when those axes have size 1, which ``ep_forward`` guards)."""
    def keep(entry):
        if entry is None:
            return None
        axes = entry if isinstance(entry, tuple) else (entry,)
        kept = tuple(a for a in axes if a in (AXIS_DP, AXIS_EP))
        return None if not kept else (kept if len(kept) > 1 else kept[0])
    return P(*[keep(e) for e in spec])


def forward(cfg: MoeConfig, params: Params, tokens: jnp.ndarray,
            mesh: Mesh | None = None, ep: bool = False,
            capacity_factor: float = 1.25) -> jnp.ndarray:
    """Full forward → logits [b, s, vocab].

    ``ep=True`` (requires ``mesh`` with an ep axis > 1) runs the
    expert-parallel dispatch path; dp·ep must divide the batch and
    ep must divide n_experts.
    """
    if not ep:
        logits, _ = _decoder_stack(
            cfg, params, tokens,
            lambda x, lp: (_moe_block(cfg, x, lp), jnp.float32(0.0)),
            jnp.float32(0.0))
        return logits
    logits, _ = ep_forward(cfg, params, tokens, mesh,
                           capacity_factor=capacity_factor)
    return logits


def ep_forward(cfg: MoeConfig, params: Params, tokens: jnp.ndarray,
               mesh: Mesh, capacity_factor: float = 1.25
               ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel forward → (logits, load_balance_aux)."""
    assert mesh is not None, "ep path needs the mesh"
    shape = dict(mesh.shape)
    ep_size = shape.get(AXIS_EP, 1)
    assert ep_size > 1, f"mesh has no ep axis > 1 (shape {shape})"
    assert cfg.n_experts % ep_size == 0, \
        f"ep={ep_size} must divide n_experts={cfg.n_experts}"
    # The shard_map body is dp×ep only: tokens and weights mention no
    # other axis, so a mesh with pp/sp/tp > 1 would silently replicate
    # the whole forward over it (N-fold wasted FLOPs plus an
    # expert-weight allgather per step). Refuse instead.
    other = {a: s for a in (AXIS_PP, AXIS_SP, AXIS_TP)
             if (s := shape.get(a, 1)) > 1}
    assert not other, (
        f"ep_forward composes with dp only; mesh has {other} — use a "
        "dp×ep mesh (MoE tensor/pipeline parallelism inside the expert "
        "shards is not implemented)")
    dp_size = shape.get(AXIS_DP, 1)
    assert tokens.shape[0] % (dp_size * ep_size) == 0, \
        f"dp*ep={dp_size * ep_size} must divide batch {tokens.shape[0]}"
    batch_spec = P((AXIS_DP, AXIS_EP))
    # Parameter placement comes from the canonical rules
    # (parallel/sharding.py param_pspecs) with the sp/tp axes collapsed:
    # the guard above pins both to size 1, and mentioning them in
    # in_specs would needlessly mark every value as varying over them
    # inside the shard_map body. Expert leaves stay P(None, ep), which
    # is exactly shard_params' placement at tp=1 — no resharding on
    # entry.
    specs = jax.tree.map(_collapse_to_dp_ep, param_pspecs(params),
                         is_leaf=lambda x: isinstance(x, P))
    fn = _shard_map(
        partial(_ep_body, cfg, capacity_factor),
        mesh=mesh,
        in_specs=(specs, batch_spec),
        out_specs=(batch_spec, batch_spec),
    )
    logits, aux_shards = fn(params, tokens)
    # [dp*ep] per-shard aux values → scalar (== the in-body pmean).
    return logits, aux_shards.mean()


def loss_fn(cfg: MoeConfig, params: Params, tokens: jnp.ndarray,
            mesh: Mesh | None = None, ep: bool = False,
            aux_weight: float = 0.01,
            capacity_factor: float = 1.25) -> jnp.ndarray:
    """Next-token loss; on the ep path the Switch load-balance auxiliary
    is added (weight 0.01, the usual setting). ``capacity_factor`` is
    the training knob for expert bucket headroom (raise it while an
    early unbalanced router is still dropping tokens)."""
    if not ep:
        return llama.next_token_loss(forward(cfg, params, tokens), tokens)
    logits, aux = ep_forward(cfg, params, tokens, mesh,
                             capacity_factor=capacity_factor)
    return llama.next_token_loss(logits, tokens) + aux_weight * aux
