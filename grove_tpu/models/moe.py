"""Mixture-of-Experts decoder (Mixtral-shaped) — the second model family.

Reuses the Llama attention stack; the MLP becomes a top-k token-choice
router over E experts. TPU-first choices:

- Experts are evaluated densely per token then combined by router weight
  (einsum over the expert axis) — static shapes, no gather/scatter of
  token groups, so XLA tiles everything onto the MXU. This is the right
  trade below ~16 experts; a capacity-based dispatch kernel is the
  pallas upgrade path for larger E.
- Expert parallelism: the ``expert`` logical axis maps to the tp mesh
  axis (grove_tpu/parallel/sharding.py), so experts shard over the same
  fast ICI group as tensor parallelism (EP == TP group).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from grove_tpu.models import llama
from grove_tpu.models.llama import LlamaConfig, _attn_out, _qkv
from grove_tpu.ops.attention import causal_attention
from grove_tpu.ops.norms import rms_norm
from grove_tpu.ops.rope import rope_table

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoeConfig(LlamaConfig):
    n_experts: int = 8
    experts_per_token: int = 2


MOE_CONFIGS: dict[str, MoeConfig] = {
    "moe-test-tiny": MoeConfig(vocab_size=256, d_model=64, n_layers=2,
                               n_heads=8, n_kv_heads=4, d_ff=96, head_dim=8,
                               max_seq_len=128, n_experts=4,
                               experts_per_token=2),
    # Mixtral-8x7B-shaped (docs/perf projections)
    "mixtral-8x7b": MoeConfig(vocab_size=32000, d_model=4096, n_layers=32,
                              n_heads=32, n_kv_heads=8, d_ff=14336,
                              head_dim=128, max_seq_len=8192, n_experts=8,
                              experts_per_token=2),
}


def init_params(cfg: MoeConfig, key: jax.Array) -> Params:
    """Llama attention/embed params plus router + stacked experts (the
    dense MLP is never allocated — for real configs it would be a
    multi-GB throwaway)."""
    base = llama.init_params(cfg, key, include_mlp=False)
    L, d, ff, E = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.n_experts
    ks = jax.random.split(jax.random.fold_in(key, 17), 4)
    layers = base["layers"]
    layers["router"] = llama.dense_init(cfg, ks[0], (L, d, E), d)
    layers["we_gate"] = llama.dense_init(cfg, ks[1], (L, E, d, ff), d)
    layers["we_up"] = llama.dense_init(cfg, ks[2], (L, E, d, ff), d)
    layers["we_down"] = llama.dense_init(cfg, ks[3], (L, E, ff, d), ff)
    return base


def _moe_block(cfg: MoeConfig, x, lp):
    """Top-k routed expert MLP with residual. x: [b, s, d]."""
    hm = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,de->bse", hm, lp["router"],
                        preferred_element_type=jnp.float32)
    k = cfg.experts_per_token
    top_vals, top_idx = lax.top_k(logits, k)                  # [b, s, k]
    gate_w = jax.nn.softmax(top_vals, axis=-1)                # [b, s, k]
    # Dense weight mask over experts: [b, s, E]
    one_hot = jax.nn.one_hot(top_idx, cfg.n_experts, dtype=gate_w.dtype)
    weights = jnp.einsum("bsk,bske->bse", gate_w, one_hot)
    # Evaluate all experts densely, combine by weight (static shapes).
    gate = jnp.einsum("bsd,edf->besf", hm, lp["we_gate"])
    up = jnp.einsum("bsd,edf->besf", hm, lp["we_up"])
    expert_out = jnp.einsum("besf,efd->besd", jax.nn.silu(gate) * up,
                            lp["we_down"])
    out = jnp.einsum("bse,besd->bsd", weights.astype(expert_out.dtype),
                     expert_out)
    return x + out.astype(x.dtype)


def forward(cfg: MoeConfig, params: Params, tokens: jnp.ndarray) -> jnp.ndarray:
    """Full forward → logits [b, s, vocab]."""
    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    x = params["tok_embed"][tokens].astype(cfg.dtype)

    def body(x, lp):
        q, k, v = _qkv(cfg, x, lp, cos, sin, positions)
        x = _attn_out(x, causal_attention(q, k, v), lp)
        x = _moe_block(cfg, x, lp)
        return x, None

    x, _ = lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                      preferred_element_type=jnp.float32)


def loss_fn(cfg: MoeConfig, params: Params, tokens: jnp.ndarray) -> jnp.ndarray:
    return llama.next_token_loss(forward(cfg, params, tokens), tokens)
