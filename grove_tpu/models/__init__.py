from grove_tpu.models.llama import (
    LlamaConfig,
    init_params,
    forward,
    prefill,
    decode_step,
    CONFIGS,
)

__all__ = [
    "LlamaConfig",
    "init_params",
    "forward",
    "prefill",
    "decode_step",
    "CONFIGS",
]
