"""Deploy bundle renderer — the Helm-chart analog (reference T1,
operator/charts/templates/*.yaml).

The reference packages its operator as a Helm chart: Deployment (+
install-crds init container), webhook configs, RBAC, a ConfigMap'd
OperatorConfiguration, and a PriorityClass. grove-tpu is a standalone
control plane, so its deploy story has two targets rendered from one
values file:

- ``gke`` — Kubernetes manifests to run the serve daemon in-cluster on a
  CPU node pool next to the TPU node pools it orchestrates: Namespace,
  ServiceAccount, PriorityClass, ConfigMap (OperatorConfiguration),
  Secret (API bearer tokens), Deployment (readiness on /healthz), and a
  Service fronting the HTTP API. Webhook configs and install-crds have
  no analog — admission is in-process and the typed API is the schema
  (PARITY.md A7/W1).
- ``systemd`` — unit + config + token env file + install script for a
  GCE controller VM (the non-k8s footprint the reference never had).

Rendering is pure: ``render_bundle(values) -> {filename: content}``;
the CLI verb ``grovectl render-deploy`` writes the files. Values load
strictly (unknown keys rejected) like the operator config itself.
"""

from __future__ import annotations

import dataclasses
import re
import secrets

import yaml

from grove_tpu.api.serde import from_dict, to_dict, unknown_keys
from grove_tpu.runtime.errors import ValidationError

_DNS_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")

AUTO_TOKEN = "auto"  # value sentinel: generate a fresh token at render


@dataclasses.dataclass
class DeployResources:
    cpu: str = "2"
    memory: str = "2Gi"


@dataclasses.dataclass
class DeployValues:
    """values.yaml schema (the chart's values analog)."""

    name: str = "grove-tpu"
    namespace: str = "grove-system"
    # gke target
    image: str = "grove-tpu:latest"
    replicas: int = 1
    priority_class: str = "grove-tpu-critical"
    priority_value: int = 1000000
    resources: DeployResources = dataclasses.field(
        default_factory=DeployResources)
    # both targets
    host: str = "0.0.0.0"
    port: int = 8087
    fleet: str = ""            # e.g. "v5e:4x4:2" (empty = discover/none)
    # actor -> token; token value "auto" generates one at render time
    tokens: dict[str, str] = dataclasses.field(
        default_factory=lambda: {"system:grove-operator": AUTO_TOKEN})
    # OperatorConfiguration overrides, embedded verbatim into the
    # rendered config file (strict-checked against the config schema).
    config: dict = dataclasses.field(default_factory=dict)
    # systemd target
    user: str = "grove"
    install_dir: str = "/opt/grove-tpu"


def load_values(path: str) -> DeployValues:
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    unknown = unknown_keys(DeployValues, data)
    if unknown:
        raise ValidationError(
            f"deploy values {path!r}: unknown keys {unknown}")
    values = from_dict(DeployValues, data)
    validate_values(values)
    return values


def validate_values(v: DeployValues) -> None:
    errs = []
    for field in ("name", "namespace"):
        val = getattr(v, field)
        if not _DNS_LABEL.match(val) or len(val) > 63:
            errs.append(f"{field} {val!r} must be a DNS label (<= 63 chars)")
    if v.replicas < 1:
        errs.append(f"replicas must be >= 1, got {v.replicas}")
    if not v.image:
        errs.append("image must not be empty")
    if not 0 < v.port < 65536:
        errs.append(f"port must be in (0, 65536), got {v.port}")
    if v.config:
        from grove_tpu.api.config import OperatorConfiguration
        unknown = unknown_keys(OperatorConfiguration, v.config)
        if unknown:
            errs.append(f"config overrides: unknown keys {unknown}")
    if errs:
        raise ValidationError("deploy values invalid: " + "; ".join(errs))


def _resolve_tokens(v: DeployValues) -> dict[str, str]:
    """actor -> concrete token (AUTO_TOKEN replaced with a fresh one)."""
    return {actor: (secrets.token_urlsafe(24) if tok == AUTO_TOKEN else tok)
            for actor, tok in v.tokens.items()}


def _operator_config_yaml(v: DeployValues) -> str:
    """The ConfigMap'd OperatorConfiguration content. Overrides are
    strict-checked in validate_values; defaults come from the dataclass
    so the rendered file is complete and self-documenting."""
    from grove_tpu.api.config import OperatorConfiguration
    cfg = to_dict(from_dict(OperatorConfiguration, v.config))
    # server_auth.tokens land in the Secret / tokens.env, never in the
    # world-readable config.
    cfg["server_auth"]["tokens"] = {}
    return yaml.safe_dump(cfg, sort_keys=False)


def _labels(v: DeployValues) -> dict[str, str]:
    return {"app.kubernetes.io/name": v.name,
            "app.kubernetes.io/managed-by": "grovectl"}


def _serve_args(v: DeployValues, config_path: str) -> list[str]:
    args = ["serve", "--host", v.host, "--port", str(v.port),
            "--config", config_path]
    if v.fleet:
        args += ["--fleet", v.fleet]
    return args


def render_gke(v: DeployValues) -> dict[str, str]:
    labels = _labels(v)
    tokens = _resolve_tokens(v)
    # token file format consumed at startup: "token,actor" per line (the
    # kube-apiserver --token-auth-file shape).
    token_lines = "".join(f"{tok},{actor}\n" for actor, tok in tokens.items())

    def manifest(obj) -> str:
        return yaml.safe_dump(obj, sort_keys=False)

    deployment = {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": v.name, "namespace": v.namespace,
                     "labels": labels},
        "spec": {
            "replicas": v.replicas,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "serviceAccountName": v.name,
                    "priorityClassName": v.priority_class,
                    "containers": [{
                        "name": "controller",
                        "image": v.image,
                        "args": _serve_args(v, "/etc/grove/config.yaml"),
                        "ports": [{"name": "api",
                                   "containerPort": v.port}],
                        "readinessProbe": {
                            "httpGet": {"path": "/healthz", "port": v.port},
                            "periodSeconds": 5},
                        "livenessProbe": {
                            "httpGet": {"path": "/healthz", "port": v.port},
                            "initialDelaySeconds": 10,
                            "periodSeconds": 10},
                        "resources": {
                            "requests": {"cpu": v.resources.cpu,
                                         "memory": v.resources.memory}},
                        "volumeMounts": [
                            {"name": "config", "mountPath": "/etc/grove"},
                            {"name": "tokens",
                             "mountPath": "/etc/grove-tokens",
                             "readOnly": True}],
                        "env": [{
                            "name": "GROVE_TOKEN_FILE",
                            "value": "/etc/grove-tokens/tokens"}],
                    }],
                    "volumes": [
                        {"name": "config",
                         "configMap": {"name": f"{v.name}-config"}},
                        {"name": "tokens",
                         "secret": {"secretName": f"{v.name}-tokens"}}],
                },
            },
        },
    }
    return {
        "namespace.yaml": manifest({
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": v.namespace, "labels": labels}}),
        "serviceaccount.yaml": manifest({
            "apiVersion": "v1", "kind": "ServiceAccount",
            "metadata": {"name": v.name, "namespace": v.namespace,
                         "labels": labels}}),
        "priorityclass.yaml": manifest({
            "apiVersion": "scheduling.k8s.io/v1", "kind": "PriorityClass",
            "metadata": {"name": v.priority_class, "labels": labels},
            "value": v.priority_value,
            "globalDefault": False,
            "description": "grove-tpu control plane priority"}),
        "configmap-operator.yaml": manifest({
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": f"{v.name}-config",
                         "namespace": v.namespace, "labels": labels},
            "data": {"config.yaml": _operator_config_yaml(v)}}),
        "secret-tokens.yaml": manifest({
            "apiVersion": "v1", "kind": "Secret",
            "metadata": {"name": f"{v.name}-tokens",
                         "namespace": v.namespace, "labels": labels},
            "type": "Opaque",
            "stringData": {"tokens": token_lines}}),
        "deployment.yaml": manifest(deployment),
        "service.yaml": manifest({
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": v.name, "namespace": v.namespace,
                         "labels": labels},
            "spec": {"selector": labels,
                     "ports": [{"name": "api", "port": v.port,
                                "targetPort": v.port}]}}),
    }


def render_systemd(v: DeployValues) -> dict[str, str]:
    tokens = _resolve_tokens(v)
    token_lines = "".join(f"{tok},{actor}\n" for actor, tok in tokens.items())
    args = " ".join(_serve_args(v, f"{v.install_dir}/config.yaml"))
    unit = f"""\
[Unit]
Description=grove-tpu control plane
After=network-online.target
Wants=network-online.target

[Service]
User={v.user}
WorkingDirectory={v.install_dir}
Environment=GROVE_TOKEN_FILE={v.install_dir}/tokens
ExecStart=/usr/bin/env python3 -m grove_tpu.cli {args}
Restart=on-failure
RestartSec=5

[Install]
WantedBy=multi-user.target
"""
    install = f"""\
#!/bin/sh
# Install the grove-tpu control plane as a systemd service.
set -eu
install -d -m 755 {v.install_dir}
install -m 644 config.yaml {v.install_dir}/config.yaml
install -m 600 tokens {v.install_dir}/tokens
install -m 644 {v.name}.service /etc/systemd/system/{v.name}.service
systemctl daemon-reload
systemctl enable --now {v.name}.service
"""
    return {
        f"{v.name}.service": unit,
        "config.yaml": _operator_config_yaml(v),
        "tokens": token_lines,
        "install.sh": install,
    }


def render_bundle(v: DeployValues, target: str) -> dict[str, str]:
    if target == "gke":
        return render_gke(v)
    if target == "systemd":
        return render_systemd(v)
    raise ValidationError(f"unknown deploy target {target!r} "
                          "(expected gke|systemd)")


def write_bundle(files: dict[str, str], out_dir: str) -> list[str]:
    import os

    os.makedirs(out_dir, exist_ok=True)
    written = []
    for name, content in sorted(files.items()):
        path = os.path.join(out_dir, name)
        with open(path, "w") as f:
            f.write(content)
        if name in ("tokens",) or name.startswith("secret-"):
            os.chmod(path, 0o600)
        written.append(path)
    return written
