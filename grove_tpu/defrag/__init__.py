"""Defragmentation engine — active placement repair (ROADMAP item 2).

PR 5 *diagnoses* why a gang cannot be placed (`Fragmented` /
`TopologyPruned` / `StragglerUnplaced`); this package *fixes* it:

- ``planner``    computes gang-atomic migration plans (move gang G from
                 its current slices onto slice T) that provably unwedge
                 a pending gang, scored by chips-freed-per-pod-moved
                 under a disruption budget;
- ``controller`` executes one plan at a time as
                 hold → drain → rebind: take a ``SliceReservation`` on
                 the target (wired to the gang through the
                 reuse-reservation-ref annotation, mirrored into
                 ``PodGang.status``), evict the gang's pods
                 gang-atomically, and let the scheduler reland them on
                 the reserved slice; abort + release cleanly on timeout
                 or target loss.

The rolling-update path takes the same reservation on a replaced pod's
freed slot (``controllers/podclique.py``) so a replacement relands in
place — deleting the PR 8 roll-wedge at the root.

``GROVE_DEFRAG=0`` (read live, per decision) disables the whole
subsystem — planner sweeps, migrations, and roll-safe holds — restoring
pre-defrag behavior exactly. See docs/design/defrag.md.
"""

from __future__ import annotations

import os

DEFRAG_ENV = "GROVE_DEFRAG"


def defrag_enabled() -> bool:
    """The subsystem kill switch, read per decision (incident
    mitigation and tests flip it live, like GROVE_EXPLAIN)."""
    return os.environ.get(DEFRAG_ENV, "1") != "0"


def migration_hold_name(gang_name: str) -> str:
    """Deterministic SliceReservation name for a defrag migration of
    ``gang_name`` (one migration per gang at a time by construction)."""
    return f"defrag-{gang_name}"


def roll_hold_name(gang_name: str) -> str:
    """Deterministic SliceReservation name for a rolling update's
    slot hold on ``gang_name``'s assigned slice."""
    return f"roll-{gang_name}"


def set_reservation_ref(client, gang_name: str, namespace: str,
                        new_ref: str,
                        expect: tuple[str, ...] | None = None) -> bool:
    """Compare-and-swap the gang's reuse-reservation-ref annotation.

    There is ONE pointer and two writers (the defrag executor and the
    roll-hold path); a blind patch from either can orphan the other's
    live hold. This helper is the only sanctioned write: it re-reads
    the gang and retries on rv conflict, so the ``expect`` check and
    the write are atomic against the store's optimistic concurrency.

    ``expect``: acceptable CURRENT values ("" = unset); None = any.
    Returns True when the annotation now equals ``new_ref`` ("" clears
    it), False when the gang is gone or another writer owns the pointer.
    """
    from grove_tpu.api import PodGang, constants as c
    from grove_tpu.runtime.errors import ConflictError, GroveError, \
        NotFoundError
    want = new_ref or ""
    for _ in range(5):
        try:
            gang = client.get(PodGang, gang_name, namespace)
        except NotFoundError:
            return False
        cur = gang.meta.annotations.get(c.ANNOTATION_RESERVATION_REF, "")
        if cur == want:
            return True
        if expect is not None and cur not in expect:
            return False
        if want:
            gang.meta.annotations[c.ANNOTATION_RESERVATION_REF] = want
        else:
            gang.meta.annotations.pop(c.ANNOTATION_RESERVATION_REF, None)
        try:
            client.update(gang)
            return True
        except ConflictError:
            continue
        except GroveError:
            return False
    return False


def release_hold(client, gang_name: str, namespace: str,
                 reservation: str) -> None:
    """The one hold-release contract, shared by every hold owner
    (defrag executor, roll path, reclaim evacuations): clear the gang's
    reuse-reservation-ref FIRST — the scheduler must stop pinning the
    gang before the fence drops — CAS'd so another writer's live
    pointer is never clobbered, then delete the reservation."""
    from grove_tpu.api import SliceReservation
    from grove_tpu.runtime.errors import GroveError, NotFoundError
    if not reservation:
        return
    set_reservation_ref(client, gang_name, namespace, "",
                        expect=(reservation,))
    try:
        client.delete(SliceReservation, reservation, namespace)
    except (NotFoundError, GroveError):
        pass


from grove_tpu.defrag.planner import (  # noqa: E402
    DEFRAG_REASONS,
    MigrationPlan,
    propose_plans,
)
from grove_tpu.defrag.controller import (  # noqa: E402
    DefragController,
    defrag_for,
)

__all__ = [
    "DEFRAG_ENV",
    "DEFRAG_REASONS",
    "DefragController",
    "MigrationPlan",
    "defrag_enabled",
    "defrag_for",
    "migration_hold_name",
    "propose_plans",
    "release_hold",
    "roll_hold_name",
    "set_reservation_ref",
]
