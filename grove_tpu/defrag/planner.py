"""Migration planning — which gang to move where, and why it helps.

Pure data-in/data-out (like ``scheduler/placement.py``): the controller
hands it the live object lists plus the host views and gets back ranked
``MigrationPlan``s. A plan is only proposed when it PROVABLY unwedges a
pending gang: both legs are verified with the real placement planner —
the victim must fit on the target slice, and the pending gang must fit
in the world where the victim's chips came home. Heuristics pick the
candidates; ``plan_gang`` decides feasibility, so the planner can never
promise a reland the scheduler would refuse.

Scoring: chips-freed-per-pod-moved (a 2-chip filler beating a 16-chip
gang teardown must mean it frees more per disruption), ties broken by
fewer pods moved, then lower victim priority.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict

from grove_tpu.api import Pod, PodGang, constants as c
from grove_tpu.scheduler.placement import HostView, PodRequest, plan_gang

# Diagnosis headlines defrag can act on: capacity exists but is in the
# wrong places. ChipShortfall/SelectorMismatch gangs need chips or label
# changes, not migrations.
DEFRAG_REASONS = frozenset(
    {"Fragmented", "TopologyPruned", "StragglerUnplaced"})

# Candidate bounds: the planner runs inside the manager at sweep
# cadence — it prunes with cheap totals and pays plan_gang only for the
# top few (victim, target) pairs.
MAX_VICTIMS = 16
MAX_TARGETS = 8


@dataclasses.dataclass
class MigrationPlan:
    """Move gang ``victim`` onto ``target_slice`` so ``pending`` fits."""

    pending_gang: str = ""
    pending_namespace: str = "default"
    victim_gang: str = ""
    victim_namespace: str = "default"
    victim_pods: list[str] = dataclasses.field(default_factory=list)
    pods_moved: int = 0
    chips_freed: int = 0
    source_slices: list[str] = dataclasses.field(default_factory=list)
    target_slice: str = ""
    score: float = 0.0           # chips_freed / pods_moved


def _live(pods: list[Pod]) -> list[Pod]:
    return [p for p in pods if p.meta.deletion_timestamp is None]


def _req(p: Pod) -> PodRequest:
    return PodRequest(p.meta.name, p.spec.tpu_chips,
                      dict(p.spec.node_selector))


def _views(hosts: list[HostView], free: dict[str, int]) -> list[HostView]:
    return [dataclasses.replace(h, free_chips=free[h.name])
            for h in hosts]


def _pack_of(gang: PodGang) -> tuple[str, bool]:
    topo = gang.spec.topology
    if topo is None:
        return "slice", True      # the scheduler's default
    return (topo.pack_level or "slice"), topo.required


def propose_plans(gangs: list[PodGang], pods: list[Pod],
                  hosts: list[HostView], *,
                  max_pods_per_plan: int,
                  max_plans: int = 4) -> list[MigrationPlan]:
    """Ranked migration plans for the currently-defrag-eligible pending
    gangs. ``max_pods_per_plan`` is the remaining disruption budget —
    victims bigger than it are never considered."""
    if max_pods_per_plan < 1:
        return []
    host_by_name = {h.name: h for h in hosts}
    base_free = {h.name: h.free_chips for h in hosts}
    slice_hosts: dict[str, list[HostView]] = defaultdict(list)
    for h in hosts:
        if h.slice_name:
            slice_hosts[h.slice_name].append(h)

    by_gang: dict[tuple[str, str], list[Pod]] = defaultdict(list)
    for p in _live(pods):
        gname = p.meta.labels.get(c.LABEL_PODGANG_NAME)
        if gname:
            by_gang[(p.meta.namespace, gname)].append(p)

    def gang_pods(g: PodGang) -> list[Pod]:
        return by_gang.get((g.meta.namespace, g.meta.name), [])

    pending: list[PodGang] = []
    victims: list[tuple[PodGang, list[Pod], int]] = []
    for g in gangs:
        if g.meta.deletion_timestamp is not None:
            continue
        if g.meta.annotations.get(c.ANNOTATION_RESERVATION_REF):
            continue    # already mid-migration or mid-roll: hands off
        diag = g.status.last_diagnosis
        if diag is not None and diag.reason in DEFRAG_REASONS:
            pending.append(g)
            continue
        mine = gang_pods(g)
        expected = [pn for grp in g.spec.groups for pn in grp.pod_names]
        by_name = {p.meta.name: p for p in mine}
        if not expected or any(pn not in by_name for pn in expected):
            continue    # mid-recreate / scaling: not safely movable
        placed = [by_name[pn] for pn in expected]
        if any(not p.status.node_name or p.spec.scheduling_gates
               or p.status.node_name not in host_by_name for p in placed):
            continue    # partially bound or on a lost node
        if any(c.LABEL_RESERVATION in p.spec.node_selector for p in placed):
            continue    # fenced to a PCS reservation: not ours to move
        if len(placed) > max_pods_per_plan:
            continue
        victims.append((g, placed, sum(p.spec.tpu_chips for p in placed)))

    if not pending or not victims:
        return []
    # Highest-value victims first: most chips freed per pod moved.
    victims.sort(key=lambda v: (-v[2] / len(v[1]), len(v[1])))
    pending.sort(key=lambda g: (-g.spec.priority,
                                g.meta.creation_timestamp))

    plans: list[MigrationPlan] = []
    for pg in pending:
        if len(plans) >= max_plans:
            break
        plan = _plan_for(pg, gang_pods(pg), victims, hosts, host_by_name,
                         base_free, slice_hosts)
        if plan is not None:
            plans.append(plan)
    plans.sort(key=lambda p: (-p.score, p.pods_moved))
    return plans


def _plan_for(pending: PodGang, pending_pods: list[Pod],
              victims, hosts, host_by_name, base_free,
              slice_hosts) -> MigrationPlan | None:
    """Best-scoring feasible migration that seats ``pending``, or None."""
    unbound = [p for p in pending_pods
               if not p.status.node_name and not p.spec.scheduling_gates]
    bound = [p for p in pending_pods if p.status.node_name]
    if not unbound:
        return None
    if any(c.LABEL_RESERVATION in p.spec.node_selector
           for p in pending_pods):
        return None     # reserved cliques live inside their own fence
    level, required = _pack_of(pending)
    anchor = ""
    if bound:
        # Straggler case: the unplaced pods must rejoin the slice their
        # siblings hold (the hard pack that makes the wedge a wedge).
        anchor = pending.status.assigned_slice
        if not anchor:
            h = host_by_name.get(bound[0].status.node_name)
            anchor = h.slice_name if h is not None else ""
        if not anchor:
            return None

    def pending_fits(after: dict[str, int]) -> bool:
        reqs = [_req(p) for p in unbound]
        if anchor:
            pool = _views(slice_hosts.get(anchor, []), after)
            return bool(pool) and plan_gang(
                reqs, pool, pack_level="slice", required=True) is not None
        return plan_gang(reqs, _views(hosts, after), pack_level=level,
                         required=required) is not None

    best: MigrationPlan | None = None
    for victim, vpods, vchips in victims[:MAX_VICTIMS]:
        if (victim.meta.namespace, victim.meta.name) == \
                (pending.meta.namespace, pending.meta.name):
            continue
        if victim.spec.priority > pending.spec.priority:
            continue    # never disrupt higher-priority work
        if best is not None and vchips / len(vpods) <= best.score:
            break       # victims are score-sorted: nothing better left
        usage: dict[str, int] = defaultdict(int)
        sources: set[str] = set()
        for p in vpods:
            usage[p.status.node_name] += p.spec.tpu_chips
            sources.add(host_by_name[p.status.node_name].slice_name)
        freed = dict(base_free)
        for node, chips in usage.items():
            freed[node] += chips
        vreqs = [_req(p) for p in vpods]
        targets = sorted(
            (s for s in slice_hosts
             if s not in sources
             and sum(freed[h.name] for h in slice_hosts[s]) >= vchips),
            key=lambda s: -sum(freed[h.name] for h in slice_hosts[s]))
        for target in targets[:MAX_TARGETS]:
            vplan = plan_gang(vreqs, _views(slice_hosts[target], freed),
                              pack_level="slice", required=True)
            if vplan is None:
                continue
            after = dict(freed)
            chips_of = {p.meta.name: p.spec.tpu_chips for p in vpods}
            for pod_name, host_name in vplan.assignments.items():
                after[host_name] -= chips_of[pod_name]
            if not pending_fits(after):
                continue
            plan = MigrationPlan(
                pending_gang=pending.meta.name,
                pending_namespace=pending.meta.namespace,
                victim_gang=victim.meta.name,
                victim_namespace=victim.meta.namespace,
                victim_pods=sorted(p.meta.name for p in vpods),
                pods_moved=len(vpods), chips_freed=vchips,
                source_slices=sorted(sources), target_slice=target,
                score=vchips / len(vpods))
            if best is None or (plan.score, -plan.pods_moved) > \
                    (best.score, -best.pods_moved):
                best = plan
            break       # targets are roomiest-first: first fit is best
    return best
