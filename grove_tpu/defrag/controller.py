"""DefragController — the hold → drain → rebind migration executor.

A manager runnable (like the deploy/serving observatories) sweeping at
``defrag.sync_period_seconds``: when a pending gang carries a
defrag-eligible diagnosis, it asks the planner for a provably-unwedging
migration and executes ONE at a time:

1. **Hold**: create a ``SliceReservation`` pinned to the target slice
   (``spec.slices``, ``spec.chips`` guarding the headroom, TTL
   backstop) and point the victim gang at it through the
   reuse-reservation-ref annotation — from here the target's free chips
   are fenced for the migrating gang and the scheduler will pin its
   reland there (``GangBackend._gang_hold``).
2. **Drain**: once the hold is BOUND (and the pending gang still needs
   it) AND the victim's disruption barrier resolved — the migration is
   a *planned* eviction, so it posts a ``DisruptionNotice`` at hold
   time and waits for the workload's checkpoint ack or the deadline
   (grove_tpu/disruption, one contract shared with the rolling-update
   and spot-reclaim paths) — delete the victim's pods gang-atomically.
   Its PodCliques recreate them gated; gates lift when the gang is
   whole again — exactly the preemption-eviction flow.
3. **Rebind**: wait for the victim to reland fully on the target slice,
   then release (annotation first — the scheduler must stop pinning
   before the fence drops — then the reservation) and poke the explain
   layer (``note_defrag_completed``) so stale pending diagnoses refresh
   ahead of GROVE_EXPLAIN_REFRESH.

Aborts (hold timeout, target loss, superseded plan, rebind timeout,
victim deleted) release the same way — a failed migration leaves the
gang free to land anywhere, never wedged on a dead hold. Disruption is
bounded: at most ``disruption_budget_pods`` evicted per
``budget_window_seconds``, one migration in flight, ``cooldown_seconds``
between starts. ``GROVE_DEFRAG=0`` stops everything (read per sweep).

Surfaces: ``GET /debug/defrag`` + ``Client/HttpClient.debug_defrag``
twins + ``grovectl defrag-status`` render :meth:`payload`;
``grove_defrag_*`` metric families count plans/chips/durations.
"""

from __future__ import annotations

import collections
import threading
import time
import weakref

from grove_tpu.api import Pod, PodGang, SliceReservation, constants as c
from grove_tpu.api.config import DefragConfig
from grove_tpu.api.meta import is_condition_true, new_meta
from grove_tpu.api.reservation import ReservationPhase, SliceReservationSpec
from grove_tpu.defrag import defrag_enabled, migration_hold_name, \
    set_reservation_ref
from grove_tpu.defrag.planner import DEFRAG_REASONS, MigrationPlan, \
    propose_plans
from grove_tpu.runtime.errors import GroveError, NotFoundError
from grove_tpu.runtime.events import EventRecorder
from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.metrics import GLOBAL_METRICS
from grove_tpu.runtime.timescale import scaled
from grove_tpu.store.client import Client

# store (weakly) -> its controller, so the in-process Client resolves
# debug_defrag without a manager reference (the deploywatch pattern).
_CONTROLLERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def defrag_for(store) -> "DefragController | None":
    return _CONTROLLERS.get(store)


class _Migration:
    """One in-flight plan's execution state."""

    __slots__ = ("plan", "state", "reservation", "started_at",
                 "drained_at", "finished_at", "outcome", "notice_id",
                 "barrier")

    def __init__(self, plan: MigrationPlan, reservation: str) -> None:
        self.plan = plan
        self.reservation = reservation
        self.state = "Holding"          # Holding | Draining | Rebinding
        self.started_at = time.time()
        self.drained_at: float | None = None
        self.finished_at: float | None = None
        self.outcome = ""               # executed | aborted:<reason>
        self.notice_id = ""             # disruption-contract barrier
        self.barrier = ""               # verdict stamped at drain

    def to_dict(self) -> dict:
        import dataclasses
        return {
            "state": self.state,
            "outcome": self.outcome,
            "reservation": self.reservation,
            "started_at": self.started_at,
            "drained_at": self.drained_at,
            "finished_at": self.finished_at,
            "notice_id": self.notice_id,
            "barrier": self.barrier,
            "plan": dataclasses.asdict(self.plan),
        }


def render_defrag_status(payload: dict, now: float | None = None
                         ) -> list[str]:
    """Human-readable defrag ledger — what ``grovectl defrag-status``
    prints. Works on the wire dict so the CLI renders identically from
    the debug endpoint and the in-process twin."""
    now = time.time() if now is None else now
    cnt = payload.get("counters", {})
    cfg = payload.get("config", {})
    lines = [
        "defrag: " + ("enabled" if payload.get("enabled")
                      else "DISABLED (GROVE_DEFRAG=0)"),
        f"  plans: {cnt.get('proposed', 0)} proposed, "
        f"{cnt.get('executed', 0)} executed, "
        f"{cnt.get('aborted', 0)} aborted; "
        f"{cnt.get('chips_freed', 0)} chips freed",
        f"  budget: {payload.get('budget_left_pods', 0)}/"
        f"{cfg.get('disruption_budget_pods', 0)} pods left in the "
        f"{cfg.get('budget_window_seconds', 0):.0f}s window",
    ]
    inflight = payload.get("inflight")
    if inflight:
        p = inflight.get("plan", {})
        age = now - inflight.get("started_at", now)
        lines.append(
            f"  in flight ({inflight.get('state', '?')}, {age:.1f}s): "
            f"gang {p.get('victim_gang', '?')} "
            f"({p.get('pods_moved', 0)} pods, "
            f"{p.get('chips_freed', 0)} chips) "
            f"{p.get('source_slices', [])} -> "
            f"{p.get('target_slice', '?')} "
            f"for {p.get('pending_gang', '?')}")
    recent = payload.get("recent") or []
    if recent:
        lines.append(f"  recent migrations ({len(recent)}, newest first):")
        for m in recent[:8]:
            p = m.get("plan", {})
            took = (m.get("finished_at") or now) - m.get("started_at", now)
            lines.append(
                f"    {m.get('outcome', '?'):18s} "
                f"{p.get('victim_gang', '?')} -> "
                f"{p.get('target_slice', '?')} "
                f"({p.get('chips_freed', 0)} chips / "
                f"{p.get('pods_moved', 0)} pods, {took:.2f}s) "
                f"for {p.get('pending_gang', '?')}")
    return lines


class DefragController:
    """Background placement-repair runnable (one per manager)."""

    RECENT_CAPACITY = 32

    def __init__(self, client: Client, store,
                 config: DefragConfig | None = None,
                 disruption_deadline_s: float | None = None,
                 barriers_enabled: bool = True) -> None:
        self.client = client
        self.store = store
        self.cfg = config or DefragConfig()
        # Checkpoint-barrier wiring for the drain: the operator's
        # disruption.default_deadline_seconds (threaded by cluster.py;
        # the dataclass default when constructed bare in tests), and
        # whether barriers apply AT ALL — disruption.enabled=False
        # removes the ack coordinator, so posting notices without it
        # would stall responder-registered gangs to expiry on every
        # migration: config-off means contract-off here too.
        if disruption_deadline_s is None:
            from grove_tpu.api.config import DisruptionConfig
            disruption_deadline_s = \
                DisruptionConfig().default_deadline_seconds
        self._disruption_deadline_s = disruption_deadline_s
        self._barriers_enabled = barriers_enabled
        self.log = get_logger("defrag")
        self.recorder = EventRecorder(client, "defrag")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Guards _active/_recent/_moved: the sweep thread mutates them,
        # payload() reads them from the HTTP server thread.
        from grove_tpu.analysis import lockdep
        self._lock = lockdep.maybe_wrap(threading.Lock(), "defrag")
        self._active: _Migration | None = None
        self._recent: collections.deque = collections.deque(
            maxlen=self.RECENT_CAPACITY)
        # (monotonic start ts, pods moved) inside the budget window.
        self._moved: collections.deque = collections.deque()
        self._last_start = 0.0          # monotonic; rate limit anchor
        self.counters = {"proposed": 0, "executed": 0, "aborted": 0,
                         "chips_freed": 0}

    # ---- runnable lifecycle ---------------------------------------------

    def start(self) -> None:
        # Registered at start (not construction): a built-but-unstarted
        # controller must not shadow the running one (deploywatch
        # precedent).
        _CONTROLLERS[self.store] = self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name="defrag",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if _CONTROLLERS.get(self.store) is self:
            del _CONTROLLERS[self.store]

    def pause(self) -> None:
        """Leadership parking (grove_tpu/ha): a demoted replica must
        not start (or continue planning) migrations — evictions from a
        fenced replica would be pure disruption."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def _run(self) -> None:
        from grove_tpu.store import writeobs
        writeobs.set_writer("defrag")
        while not self._stop.is_set():
            if getattr(self, "_paused", False):
                self._stop.wait(self.cfg.sync_period_seconds)
                continue
            try:
                self.sweep()
            except Exception:   # noqa: BLE001 — loop survival barrier
                self.log.exception("defrag sweep panicked")
            self._stop.wait(self.cfg.sync_period_seconds)

    # ---- the sweep -------------------------------------------------------

    def sweep(self) -> None:
        """One decision round: advance the in-flight migration, else
        plan and start a new one. Public so tests and tools can drive
        it synchronously."""
        if not defrag_enabled():
            if self._active is not None:
                self._abort(self._active, "disabled")
            GLOBAL_METRICS.set("grove_defrag_inflight", 0.0)
            return
        if self._active is not None:
            self._advance(self._active)
        GLOBAL_METRICS.set("grove_defrag_inflight",
                           1.0 if self._active is not None else 0.0)
        if self._active is not None:
            return                      # one migration at a time
        now = time.monotonic()
        if now - self._last_start < self.cfg.cooldown_seconds:
            return
        budget_left = self._budget_left(now)
        if budget_left < 1:
            return
        gangs = self.client.list(PodGang, None)
        if not any(g.status.last_diagnosis is not None
                   and g.status.last_diagnosis.reason in DEFRAG_REASONS
                   and g.meta.deletion_timestamp is None
                   for g in gangs):
            return                      # cheap early exit: nothing stuck
        from grove_tpu.scheduler.backends import DEFAULT_LEVEL_LABELS, \
            build_host_views
        pods = self.client.list(Pod, None)
        hosts = build_host_views(self.client, None, DEFAULT_LEVEL_LABELS)
        plans = propose_plans(gangs, pods, hosts,
                              max_pods_per_plan=budget_left)
        if plans:
            self._start_migration(plans[0])

    def _budget_left(self, now: float) -> int:
        window = self.cfg.budget_window_seconds
        with self._lock:
            while self._moved and now - self._moved[0][0] > window:
                self._moved.popleft()
            return self.cfg.disruption_budget_pods - sum(
                n for _, n in self._moved)

    # ---- execution -------------------------------------------------------

    def _start_migration(self, plan: MigrationPlan) -> None:
        name = migration_hold_name(plan.victim_gang)
        ns = plan.victim_namespace
        rsv = SliceReservation(
            meta=new_meta(name, namespace=ns, labels={
                c.LABEL_MANAGED_BY: c.LABEL_MANAGED_BY_VALUE,
                c.LABEL_HOLD_FOR_GANG: plan.victim_gang,
            }),
            spec=SliceReservationSpec(
                slices=[plan.target_slice], chips=plan.chips_freed,
                ttl_seconds=scaled(self.cfg.hold_ttl_seconds)))
        try:
            self.client.create(rsv)
        except GroveError as e:
            # A leftover hold with this name (aborted run's TTL still
            # ticking) blocks the retry; skip this sweep — the TTL or
            # the gang-delete GC clears it.
            self.log.warning("defrag hold %s not created: %s", name, e)
            return
        # CAS from unset only: the planner's no-annotation filter ran
        # against a pass-start snapshot, and the roll-hold path may have
        # claimed the gang since — never clobber a live pointer.
        if not set_reservation_ref(self.client, plan.victim_gang, ns,
                                   name, expect=("",)):
            self.log.warning("defrag ref on %s not set (gang gone or "
                             "another hold claimed it)", plan.victim_gang)
            self._delete_reservation(name, ns)
            return
        m = _Migration(plan, name)
        # The disruption contract: declare the planned eviction NOW so
        # the workload's checkpoint runs in parallel with the hold
        # binding (one barrier protocol for defrag, rolls, and spot
        # reclaim — docs/design/disruption-contract.md).
        self._post_barrier(m)
        with self._lock:
            self._active = m
        self._last_start = time.monotonic()
        self.counters["proposed"] += 1
        GLOBAL_METRICS.inc("grove_defrag_plans_proposed_total")
        self.log.info(
            "defrag: migrating gang %s (%d pods, %d chips) from %s to %s "
            "to unwedge %s (score %.2f)", plan.victim_gang,
            plan.pods_moved, plan.chips_freed, plan.source_slices,
            plan.target_slice, plan.pending_gang, plan.score)
        self._event(plan.victim_gang, ns, "Normal", "DefragMigrationStarted",
                    f"migrating {plan.pods_moved} pod(s) from "
                    f"{plan.source_slices} to {plan.target_slice} to "
                    f"unwedge gang {plan.pending_gang} "
                    f"(chips-freed-per-pod {plan.score:.1f})")

    def _post_barrier(self, m: _Migration) -> bool:
        """Post (or re-post after write contention) the migration's
        disruption notice. True once the barrier question is settled
        (notice posted, or contract disabled / victim gone); False
        means a contended write — retry next sweep, never drain."""
        from grove_tpu.disruption import REASON_DEFRAG, request_barrier
        if not self._barriers_enabled:
            m.barrier = "disabled"
            return True
        state, notice = request_barrier(
            self.client, m.plan.victim_gang, m.plan.victim_namespace,
            REASON_DEFRAG, self._disruption_deadline_s)
        if notice is not None:
            m.notice_id = notice.id
            return True
        if state in ("disabled", "gone"):
            m.barrier = "disabled"
            return True
        return False            # "retry": contended annotation

    def _advance(self, m: _Migration) -> None:
        plan = m.plan
        ns = plan.victim_namespace
        try:
            gang = self.client.get(PodGang, plan.victim_gang, ns)
        except NotFoundError:
            self._abort(m, "victim-gone")
            return
        if m.state == "Holding":
            try:
                rsv = self.client.get(SliceReservation, m.reservation, ns)
            except NotFoundError:
                self._abort(m, "hold-lost")
                return
            if rsv.status.phase == ReservationPhase.BOUND \
                    and rsv.status.bound_slices:
                if not self._pending_still_needs(plan):
                    self._abort(m, "superseded")
                    return
                if not m.notice_id and m.barrier != "disabled":
                    # The initial post lost every CAS round (contended
                    # annotation): re-post — write contention must
                    # never silently strip the barrier and drain an
                    # un-noticed gang while the contract is enabled.
                    if not self._post_barrier(m):
                        return
                if m.notice_id:
                    # The checkpoint barrier: drain only once the
                    # victim acked (or the deadline expired — the
                    # workload delays, never vetoes). The notice
                    # self-expires, so this wait is bounded.
                    from grove_tpu.disruption import barrier_state, \
                        notice_of
                    state = barrier_state(notice_of(gang))
                    if state == "pending":
                        return
                self._drain(m, gang)
                return
            if time.time() - m.started_at > \
                    scaled(self.cfg.hold_timeout_seconds):
                self._abort(m, "hold-timeout")
            return
        if m.state == "Rebinding":
            relanded = (
                is_condition_true(gang.status.conditions, c.COND_SCHEDULED)
                and gang.status.assigned_slice == plan.target_slice
                and self._fully_bound(gang))
            if relanded:
                self._complete(m)
                return
            try:
                self.client.get(SliceReservation, m.reservation, ns)
            except NotFoundError:
                # Target lost mid-reland (TTL, slice death): release the
                # pin so the gang may land anywhere.
                self._abort(m, "target-lost")
                return
            if time.time() - (m.drained_at or m.started_at) > \
                    scaled(self.cfg.rebind_timeout_seconds):
                self._abort(m, "rebind-timeout")

    def _drain(self, m: _Migration, gang: PodGang) -> None:
        """Gang-atomic eviction: every victim pod deleted in one round —
        the PodCliques recreate them gated, so mid-migration the gang
        only ever has FEWER pods bound than before, never a second live
        copy (the chaos no-duplicates/gang-binding invariants hold).
        The barrier verdict is stamped onto the notice FIRST — the
        disruption-contract invariant's audit record."""
        plan = m.plan
        if m.notice_id:
            from grove_tpu.disruption import note_evicted
            stamped = note_evicted(self.client, plan.victim_gang,
                                   plan.victim_namespace, m.notice_id)
            if stamped:
                m.barrier = stamped
        pods = self.client.list(
            Pod, plan.victim_namespace,
            selector={c.LABEL_PODGANG_NAME: plan.victim_gang})
        for p in pods:
            if p.meta.deletion_timestamp is not None:
                continue
            try:
                self.client.delete(Pod, p.meta.name, p.meta.namespace)
            except (NotFoundError, GroveError):
                pass
        with self._lock:
            self._moved.append((time.monotonic(), plan.pods_moved))
        m.state = "Rebinding"
        m.drained_at = time.time()

    def _pending_still_needs(self, plan: MigrationPlan) -> bool:
        """The pending gang must still be stuck for a defrag-eligible
        reason — a gang that scheduled (capacity appeared elsewhere) or
        vanished makes the migration pure churn."""
        try:
            pg = self.client.get(PodGang, plan.pending_gang,
                                 plan.pending_namespace)
        except NotFoundError:
            return False
        if is_condition_true(pg.status.conditions, c.COND_SCHEDULED) \
                and pg.status.last_diagnosis is None:
            return False
        return True

    def _fully_bound(self, gang: PodGang) -> bool:
        expected = [pn for grp in gang.spec.groups for pn in grp.pod_names]
        pods = {p.meta.name: p for p in self.client.list(
            Pod, gang.meta.namespace,
            selector={c.LABEL_PODGANG_NAME: gang.meta.name})
            if p.meta.deletion_timestamp is None}
        return bool(expected) and all(
            pn in pods and pods[pn].status.node_name for pn in expected)

    # ---- completion / abort ----------------------------------------------

    def _complete(self, m: _Migration) -> None:
        plan = m.plan
        self._release(m)
        duration = time.time() - m.started_at
        m.state, m.outcome = "Done", "executed"
        m.finished_at = time.time()
        self._finish(m)
        self.counters["executed"] += 1
        self.counters["chips_freed"] += plan.chips_freed
        GLOBAL_METRICS.inc("grove_defrag_plans_executed_total")
        GLOBAL_METRICS.inc("grove_defrag_chips_freed_total",
                           plan.chips_freed)
        GLOBAL_METRICS.observe("grove_defrag_migration_seconds", duration)
        # The world every pending diagnosis describes just changed:
        # force the next attempt to re-judge instead of waiting out
        # GROVE_EXPLAIN_REFRESH (the unschedulable gauges read the
        # persisted diagnosis).
        from grove_tpu.scheduler.explain import note_defrag_completed
        note_defrag_completed()
        self.log.info("defrag: gang %s relanded on %s in %.2fs "
                      "(%d chips freed for %s)", plan.victim_gang,
                      plan.target_slice, duration, plan.chips_freed,
                      plan.pending_gang)
        self._event(plan.victim_gang, plan.victim_namespace, "Normal",
                    "DefragMigrationCompleted",
                    f"relanded on {plan.target_slice} in {duration:.2f}s; "
                    f"{plan.chips_freed} chips freed on "
                    f"{plan.source_slices} for gang {plan.pending_gang}")

    def _abort(self, m: _Migration, reason: str) -> None:
        at_state = m.state
        self._release(m)
        m.state, m.outcome = "Aborted", f"aborted:{reason}"
        m.finished_at = time.time()
        self._finish(m)
        self.counters["aborted"] += 1
        GLOBAL_METRICS.inc("grove_defrag_plans_aborted_total",
                           reason=reason)
        if m.drained_at is not None:
            # Pods were already moved: the fleet state still changed,
            # so stale diagnoses must re-judge it.
            from grove_tpu.scheduler.explain import note_defrag_completed
            note_defrag_completed()
        self.log.warning("defrag: migration of %s aborted (%s) at %s",
                         m.plan.victim_gang, reason, at_state)
        self._event(m.plan.victim_gang, m.plan.victim_namespace, "Warning",
                    "DefragMigrationAborted",
                    f"migration to {m.plan.target_slice} aborted "
                    f"({reason}); hold released")

    def _release(self, m: _Migration) -> None:
        """The shared annotation-first release contract
        (defrag.release_hold). The disruption notice goes with it
        (id-CAS'd the same way) so the gang does not keep wearing a
        phantom barrier."""
        from grove_tpu.defrag import release_hold
        release_hold(self.client, m.plan.victim_gang,
                     m.plan.victim_namespace, m.reservation)
        if m.notice_id:
            from grove_tpu.disruption import clear_notice
            clear_notice(self.client, m.plan.victim_gang,
                         m.plan.victim_namespace, m.notice_id)

    def _delete_reservation(self, name: str, namespace: str) -> None:
        try:
            self.client.delete(SliceReservation, name, namespace)
        except (NotFoundError, GroveError):
            pass

    def _finish(self, m: _Migration) -> None:
        with self._lock:
            self._recent.appendleft(m.to_dict())
            self._active = None

    def _event(self, gang_name: str, namespace: str, etype: str,
               reason: str, message: str) -> None:
        try:
            gang = self.client.get(PodGang, gang_name, namespace)
        except (NotFoundError, GroveError):
            return
        self.recorder.event(gang, etype, reason, message)

    # ---- read surface ----------------------------------------------------

    def payload(self) -> dict:
        """The /debug/defrag wire shape (grovectl defrag-status renders
        it; one shape in-process and over HTTP)."""
        budget_left = self._budget_left(time.monotonic())
        with self._lock:
            inflight = (self._active.to_dict()
                        if self._active is not None else None)
            recent = list(self._recent)
        return {
            "enabled": defrag_enabled(),
            "config": {
                "sync_period_seconds": self.cfg.sync_period_seconds,
                "disruption_budget_pods": self.cfg.disruption_budget_pods,
                "budget_window_seconds": self.cfg.budget_window_seconds,
                "cooldown_seconds": self.cfg.cooldown_seconds,
            },
            "counters": dict(self.counters),
            "budget_left_pods": budget_left,
            "inflight": inflight,
            "recent": recent,
        }
