"""YAML manifest loading — the kubectl-apply surface.

Maps YAML documents (kind + metadata + spec, snake_case fields mirroring
the dataclass API) onto typed resources. The reference relies on kubectl
+ CRD schemas; here the manifest codec is part of the framework.
"""

from __future__ import annotations

from typing import Any, TextIO

import yaml

from grove_tpu.api import (
    ClusterTopology,
    Node,
    Pod,
    PodClique,
    PodCliqueScalingGroup,
    PodCliqueSet,
    PodGang,
    SliceReservation,
)
from grove_tpu.api.core import Secret, Service
from grove_tpu.api.meta import ObjectMeta, new_meta
from grove_tpu.api.serde import from_dict, type_problems, unknown_keys
from grove_tpu.runtime.errors import ValidationError
from grove_tpu.runtime.events import Event

KIND_REGISTRY: dict[str, type] = {
    cls.KIND: cls
    for cls in (PodCliqueSet, PodClique, PodCliqueScalingGroup, PodGang,
                ClusterTopology, Pod, Node, Service, Event, SliceReservation,
                Secret)
}


def load_object(doc: dict[str, Any]) -> Any:
    kind = doc.get("kind")
    cls = KIND_REGISTRY.get(kind or "")
    if cls is None:
        raise ValidationError(
            f"unknown kind {kind!r}; supported: {sorted(KIND_REGISTRY)}")
    metadata = doc.get("metadata") or {}
    if not metadata.get("name"):
        raise ValidationError(f"{kind}: metadata.name is required")
    obj = cls()
    obj.meta = new_meta(metadata["name"],
                        namespace=metadata.get("namespace", "default"),
                        labels=metadata.get("labels"),
                        annotations=metadata.get("annotations"))
    if "spec" in doc:
        spec_cls = type(obj.spec) if hasattr(obj, "spec") else None
        if spec_cls is None:
            raise ValidationError(f"{kind} does not take a spec")
        # Strict decode, same posture as the operator config: a typo'd
        # key silently becoming a default is the worst failure mode, and
        # from_dict passes wrong-typed scalars through untouched.
        unknown = unknown_keys(spec_cls, doc["spec"], prefix="spec")
        if unknown:
            raise ValidationError(f"{kind}: unknown keys {unknown}")
        obj.spec = from_dict(spec_cls, doc["spec"])
        problems = type_problems(obj.spec, prefix="spec")
        if problems:
            raise ValidationError(f"{kind}: " + "; ".join(problems))
    return obj


def load_manifest(stream: str | TextIO) -> list[Any]:
    """Parse a (multi-document) YAML manifest into typed objects."""
    docs = yaml.safe_load_all(stream)
    return [load_object(d) for d in docs if d]
