"""Versioned, watchable object store — the control plane's state core.

The reference runs against kube-apiserver + etcd; this framework carries
its own equivalent: optimistic concurrency via resource_version, spec
generation bumping, finalizer-aware deletion, owner-reference cascade
deletion (the k8s GC analog), label-selector lists, and watch streams
with per-watcher queues (the informer feed).

Thread-safe; controllers run in threads and see a consistent snapshot per
call (objects are deep-cloned across the boundary, so callers can never
mutate store state in place — the informer-cache-corruption class of bug
is structurally impossible).
"""

from __future__ import annotations

import collections
import enum
import itertools
import queue
import threading
import time
import uuid
from typing import Any, Callable, Iterable, NamedTuple

from grove_tpu.api.serde import clone, to_dict
from grove_tpu.runtime.trace import GLOBAL_TRACER
from grove_tpu.runtime.errors import (
    AlreadyExistsError,
    ConflictError,
    FencedError,
    NotFoundError,
    ValidationError,
)
from grove_tpu.store import writeobs


class EventType(str, enum.Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


class Event(NamedTuple):
    type: EventType
    obj: Any
    # Emission wall time, stamped by Store._emit. Informers observe
    # apply-time minus this as grove_informer_event_lag_seconds; 0.0
    # means "unknown" (synthetic events built by tests/resync mappers).
    ts: float = 0.0


def _key(obj: Any) -> tuple[str, str]:
    return (obj.meta.namespace, obj.meta.name)


def matches_labels(obj: Any, selector: dict[str, str] | None) -> bool:
    if not selector:
        return True
    labels = obj.meta.labels
    return all(labels.get(k) == v for k, v in selector.items())


def matches_fields(obj: Any, fields: dict[str, str] | None) -> bool:
    """Status-field selector (kube fieldSelector analog): every key must
    match one of its comma-separated values. Enum values compare by
    their wire value; missing fields compare as ''. ONE implementation
    shared by the in-process list and the HTTP list handler."""
    if not fields:
        return True
    st = getattr(obj, "status", None)
    for key, want in fields.items():
        v = getattr(st, key, "") if st is not None else ""
        if hasattr(v, "value"):
            v = v.value
        if str(v) not in set(str(want).split(",")):
            return False
    return True


class Watcher:
    """A subscription to store events; iterate or poll with timeout."""

    def __init__(self, kinds: set[str] | None, selector: dict[str, str] | None):
        self.kinds = kinds
        self.selector = selector
        self.queue: "queue.Queue[Event]" = queue.Queue()
        self.closed = False

    def _offer(self, event: Event) -> None:
        if self.closed:
            return
        if self.kinds is not None and event.obj.KIND not in self.kinds:
            return
        if not matches_labels(event.obj, self.selector):
            return
        self.queue.put(event)

    def poll(self, timeout: float | None = 0.5) -> Event | None:
        try:
            return self.queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def close(self) -> None:
        self.closed = True


class _WriteGuard:
    """Context guard for one instrumented store write verb (see
    ``Store._locked_write``): times lock wait/hold around the store
    lock and flushes the thread's write record after release. Slotted
    and hand-rolled for per-write cost — this is the hottest object on
    the write path."""

    __slots__ = ("_store", "_rec", "_t1")

    def __init__(self, store: "Store", verb: str) -> None:
        self._store = store
        self._rec = writeobs.begin(verb)

    def __enter__(self) -> None:
        if self._rec is None:
            self._store._lock.acquire()
            return
        t0 = time.perf_counter()
        self._store._lock.acquire()
        self._t1 = time.perf_counter()
        self._rec.wait_s = self._t1 - t0

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._store._lock.release()
        rec = self._rec
        if rec is not None:
            rec.hold_s = time.perf_counter() - self._t1
            writeobs.flush(rec)
        return False


class Store:
    def __init__(self, state_dir: str | None = None,
                 takeover_wait: bool = False,
                 warm: tuple[dict, int] | None = None) -> None:
        """``warm=(objects_by_key, rv)`` is the hot standby's promotion
        fast path: the caller's wire mirror already holds exact store
        state at ``rv``, so loading replays only the WAL delta past it
        (``StatePersister.load_warm``) instead of decoding snapshot +
        full WAL; falls back to the full load whenever equivalence
        cannot be proven. Ignored without ``state_dir``."""
        # Wrapped by the lock-order witness under GROVE_LOCKDEP=1
        # (grove_tpu/analysis/lockdep.py); the raw RLock otherwise.
        from grove_tpu.analysis import lockdep
        self._lock = lockdep.maybe_wrap(threading.RLock(), "store")
        # Signalled on every _emit: wire long-polls block on this instead
        # of rescanning the ring on a poll interval.
        self._event_cond = threading.Condition(self._lock)
        self._objects: dict[str, dict[tuple[str, str], Any]] = {}
        self._rv = itertools.count(1)
        self._watchers: list[Watcher] = []
        self._admission = None   # AdmissionChain (see grove_tpu.admission)
        # Read-path clone cache: stored objects are immutable per
        # resource version (writes REPLACE entries, never mutate), so
        # the pickle-dumps half of every read clone can be computed once
        # per version and reused by every subsequent reader — at steady
        # state reconcilers re-read far more than controllers write
        # (profiled: serde.clone dominated the 1000-pod no-op reconcile
        # cost). Keyed by object identity; entries die with the object.
        self._clone_cache: dict[tuple[str, str, str],
                                tuple[int, bytes]] = {}
        # Snapshot read path (list_snapshot): per-version MATERIALIZED
        # clones, shared across readers that promise not to mutate —
        # skips even the pickle.loads half for read-mostly consumers
        # (the scheduler's placement snapshot). Invalidation is by
        # resource version, eviction with the object (_remove).
        self._snapshot_cache: dict[tuple[str, str, str],
                                   tuple[int, Any]] = {}
        # Read-path observability: every list-shaped read that scans a
        # kind's object dict counts here (list + list_snapshot). The
        # reconcile bench asserts the informer path's scan reduction
        # from this counter, not from private controller state.
        self.list_scans = 0
        # Event history ring for resumable (wire) watches: (seq, event).
        # seq is the rv that produced the event (deletes allocate one).
        # A watcher further behind than the ring must relist (410-Gone
        # semantics, exactly the kube watch contract).
        self._history: collections.deque[tuple[int, Event]] = \
            collections.deque(maxlen=4096)
        # Leadership fencing epoch (grove_tpu/ha, proposal 0002): the
        # monotonic term number. Writes that carry an epoch older than
        # this are rejected (FencedError) — the zombie-deposed-leader
        # guard. 0 = no leadership transition has ever fenced this
        # store; writers without an epoch (None — user clients, agents)
        # are never fenced.
        self._epoch = 0
        # Durability (etcd analog, store/persist.py): WAL every mutation,
        # snapshot compaction, full state restore on construction.
        self._persister = None
        if state_dir is not None:
            from grove_tpu.store.persist import StatePersister
            self._persister = StatePersister(state_dir,
                                             takeover_wait=takeover_wait)
            loaded = None
            if warm is not None:
                loaded = self._persister.load_warm(warm[0], warm[1])
            if loaded is None:
                loaded = self._persister.load()
            objects, max_rv, self._epoch = loaded
            for obj in objects:
                self._objects.setdefault(obj.KIND, {})[_key(obj)] = obj
            self._rv = itertools.count(max_rv + 1)

    def _locked_write(self, verb: str) -> "_WriteGuard":
        """The store lock, instrumented for the write path: opens a
        per-thread telemetry record (writer attribution, commit/noop/
        conflict/event notes from the locked internals), times lock
        wait and hold, and flushes everything to the metrics hub in one
        batch AFTER release — per-sample hub incs under this lock would
        stall every writer behind each /metrics render. With
        ``GROVE_WRITE_OBS=0`` this degrades to the bare lock. A slotted
        guard class, not a @contextmanager: generator-based context
        managers cost ~2µs per use, and this wraps EVERY store write —
        including the no-op status write every steady-state reconcile
        ends in, where that overhead erodes the PR 2 informer
        steady-sweep ratio."""
        return _WriteGuard(self, verb)

    # ---- leadership fencing (grove_tpu/ha, proposal 0002) ----

    def fencing_epoch(self) -> int:
        """The store's current fencing epoch (term number)."""
        with self._lock:
            return self._epoch

    def bump_epoch(self) -> int:
        """Advance the fencing epoch — THE promotion action: after this
        returns (durably, when persistent), any write still carrying
        the previous epoch is rejected. Returns the new epoch."""
        with self._lock:
            self._epoch += 1
            if self._persister is not None:
                self._persister.record_epoch(self._epoch)
            epoch = self._epoch
        from grove_tpu.runtime.metrics import GLOBAL_METRICS
        GLOBAL_METRICS.set("grove_leadership_epoch", float(epoch))
        return epoch

    def _check_fence(self, kind: str, verb: str,
                     epoch: int | None) -> None:
        """Reject a write whose writer claims a stale epoch (called
        under the lock, before admission — a deposed leader gets the
        fence, not a validation error). ``None`` = an unfenced writer
        (user clients, node agents): leadership never gates those.
        GROVE_HA=0 disables the check entirely."""
        if epoch is None or epoch >= self._epoch:
            return
        from grove_tpu.ha import ha_enabled
        if not ha_enabled():
            return
        writeobs.note_fenced(kind, verb)
        raise FencedError(
            f"{kind} {verb} fenced: writer epoch {epoch} predates the "
            f"store's fencing epoch {self._epoch} — a newer leader has "
            "taken over; this writer must stand down")

    def _persist_put(self, obj: Any) -> None:
        if self._persister is not None:
            self._persister.record_put(obj, epoch=self._epoch)
            self._maybe_compact()

    def _persist_delete(self, obj: Any, rv: int = 0) -> None:
        if self._persister is not None:
            self._persister.record_delete(obj, rv=rv, epoch=self._epoch)
            self._maybe_compact()

    def _maybe_compact(self) -> None:
        # Called under the lock: the object view handed to the persister
        # is consistent, and stored objects are never mutated in place.
        self._persister.maybe_compact(
            [o for objs in self._objects.values() for o in objs.values()],
            rv=self._peek_rv(), epoch=self._epoch)

    def compact_now(self) -> bool:
        """Synchronously fold the WAL into a snapshot, regardless of
        the threshold — the operational pre-backup / pre-handoff
        surface (and what the failover bench uses to keep a compaction
        rotation out of its kill window). False without persistence."""
        with self._lock:
            if self._persister is None:
                return False
            self._persister.compact(
                [o for objs in self._objects.values()
                 for o in objs.values()],
                rv=self._peek_rv(), epoch=self._epoch)
            return True

    def _peek_rv(self) -> int:
        # itertools.count has no peek; track via a probe-and-restore.
        rv = next(self._rv)
        self._rv = itertools.count(rv)
        return rv - 1

    def set_admission(self, chain) -> None:
        self._admission = chain

    def _admit(self, verb: str, obj: Any, old: Any, actor: str) -> Any:
        if self._admission is None:
            return obj
        return self._admission.admit(verb, obj, old, actor)

    def dry_run_admit(self, obj: Any,
                      actor: str = "system:grove-operator") -> str:
        """Run the FULL admission chain for a would-be create-or-update
        of ``obj`` against live state, committing nothing (the kubectl
        --dry-run=server analog). ONE admission path: this is the same
        _admit the real writes call, with the same create-vs-update
        decision, under the same lock. Returns "would-create" or
        "would-update"; raises exactly what the real write would."""
        with self._lock:
            live = self._objects.get(obj.KIND, {}).get(_key(obj))
            if live is None:
                self._admit("create", clone(obj), None, actor)
                return "would-create"
            updated = clone(live)
            updated.spec = clone(obj.spec)
            self._admit("update", updated, clone(live), actor)
            return "would-update"

    # ---- watch ----

    def watch(self, kinds: Iterable[str] | None = None,
              selector: dict[str, str] | None = None) -> Watcher:
        w = Watcher(set(kinds) if kinds is not None else None, selector)
        with self._lock:
            self._watchers.append(w)
        return w

    def _emit(self, etype: EventType, obj: Any, seq: int | None = None) -> None:
        # One clone shared by all watchers AND the history ring: event
        # payloads are read-only by convention (mappers extract
        # names/labels; reconcilers re-read through the client, never
        # mutate event objects).
        shared = Event(etype, clone(obj), time.time())
        self._history.append(
            (obj.meta.resource_version if seq is None else seq, shared))
        for w in self._watchers:
            w._offer(shared)
        self._event_cond.notify_all()
        writeobs.note_event(obj.KIND, etype.value)

    def current_rv(self) -> int:
        """The highest resource version issued so far (watch bootstrap)."""
        with self._lock:
            return self._peek_rv()

    def wait_events(self, since: int, timeout: float) -> None:
        """Block until the ring holds an event with seq > ``since`` or
        ``timeout`` elapses — the wire long-poll's wakeup (no ring
        rescan per poll tick; _emit notifies)."""
        with self._event_cond:
            self._event_cond.wait_for(
                lambda: bool(self._history
                             and self._history[-1][0] > since),
                timeout=timeout)

    def replay(self, since: int,
               kinds: set[str] | None = None,
               namespace: str | None = None,
               selector: dict[str, str] | None = None
               ) -> tuple[list[tuple[int, Event]], bool, int]:
        """Events with seq > ``since``, filtered. Returns
        (events, ok, scanned): ok=False means ``since`` predates the
        ring (the caller must relist — kube's 410 Gone); ``scanned`` is
        the highest seq examined (>= since), which the caller MUST use
        as its next resume point even when every event was filtered out
        — resuming at the last *matching* seq pins the cursor while
        unrelated events wrap the ring, turning a quiet filtered watch
        into a spurious 410. Seqs are consecutive (every allocated rv
        emits exactly one event; no-op suppression allocates none), so
        history is lost iff the first retained seq skips past
        ``since + 1`` — or the ring is empty while events have happened
        (e.g. a persistent store freshly rebooted)."""
        with self._lock:
            if self._history:
                # Fast path for caught-up cursors: informers sync on
                # every cached read, so "nothing new" must not pay the
                # islice skip-walk over the whole ring.
                if self._history[-1][0] <= since:
                    return [], True, since
                if since + 1 < self._history[0][0]:
                    return [], False, since
            elif since < self._peek_rv():
                return [], False, since
            out = []
            scanned = since
            # Seqs are consecutive, so the resume offset is arithmetic —
            # no head-scan past already-delivered entries (at 1000-pod
            # churn the skip-scan would dominate every long-poll).
            start = max(0, since + 1 - self._history[0][0]) \
                if self._history else 0
            for seq, ev in itertools.islice(self._history, start, None):
                if seq <= since:
                    continue
                scanned = max(scanned, seq)
                if kinds is not None and ev.obj.KIND not in kinds:
                    continue
                if namespace is not None \
                        and ev.obj.meta.namespace != namespace:
                    continue
                if not matches_labels(ev.obj, selector):
                    continue
                out.append((seq, ev))
            return out, True, scanned

    # ---- reads ----

    # Stored objects are never mutated in place after insertion (writes
    # replace the dict entry with a fresh clone) — so reads may snapshot
    # references under the lock and clone OUTSIDE it. Cloning N objects
    # inside the global lock would serialise every controller thread
    # behind each large list.

    def _read_clone(self, obj: Any) -> Any:
        """Clone for the read path via the per-version bytes cache (one
        pickle.dumps per object version; loads per reader)."""
        import pickle
        key = (obj.KIND, obj.meta.namespace, obj.meta.name)
        rv = obj.meta.resource_version
        hit = self._clone_cache.get(key)
        if hit is not None and hit[0] == rv:
            return pickle.loads(hit[1])
        data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        with self._lock:
            # Insert under the lock, re-checked against live objects:
            # an unlocked insert could race _remove's eviction and
            # resurrect a just-deleted entry forever (the rv compare
            # keeps correctness either way; this keeps the cache from
            # leaking dead names).
            if _key(obj) in self._objects.get(obj.KIND, {}):
                self._clone_cache[key] = (rv, data)
        return pickle.loads(data)

    def _shared_clone(self, obj: Any) -> Any:
        """A per-version cached clone SHARED across snapshot readers.
        One pickle.dumps+loads per object version total (vs. one loads
        per reader in _read_clone); callers must honor the read-only
        contract of list_snapshot."""
        key = (obj.KIND, obj.meta.namespace, obj.meta.name)
        rv = obj.meta.resource_version
        hit = self._snapshot_cache.get(key)
        if hit is not None and hit[0] == rv:
            return hit[1]
        out = self._read_clone(obj)
        with self._lock:
            # Same eviction race discipline as _read_clone: only cache
            # names that are still live, so deleted objects cannot be
            # resurrected into the cache forever.
            if _key(obj) in self._objects.get(obj.KIND, {}):
                self._snapshot_cache[key] = (rv, out)
        return out

    def list_snapshot(self, kind_cls: type,
                      namespace: str | None = "default",
                      selector: dict[str, str] | None = None
                      ) -> tuple[int, list[Any]]:
        """Cheap list for read-mostly consumers: ``(rv, objects)`` where
        ``rv`` is the store's resource version at snapshot time and the
        objects are per-version cached clones SHARED with every other
        ``list_snapshot`` caller.

        Contract: callers MUST NOT mutate the returned objects (clone()
        before editing — the scheduler's bind path does exactly that).
        In exchange, a steady-state list costs one dict scan plus cache
        lookups: no per-reader ``pickle.loads`` (the cost profiled to
        dominate the naive O(gangs x pods) placement pass). The rv lets
        the consumer detect outside writes (``current_rv() != rv``) and
        decide when its derived state needs a rebuild."""
        with self._lock:
            self.list_scans += 1
            rv = self._peek_rv()
            objs = self._objects.get(kind_cls.KIND, {})
            refs = [obj for (ns, _), obj in objs.items()
                    if (namespace is None or ns == namespace)
                    and matches_labels(obj, selector)]
        self._count_scan(kind_cls.KIND)
        out = [self._shared_clone(o) for o in refs]
        out.sort(key=lambda o: o.meta.name)
        return rv, out

    @staticmethod
    def _count_scan(kind: str) -> None:
        """Metric twin of the ``list_scans`` attribute, counted OUTSIDE
        the store lock (the hub lock is held across /metrics renders)
        and gated with the write-path telemetry."""
        writeobs.count_scan(kind)

    def get(self, kind_cls: type, name: str, namespace: str = "default") -> Any:
        with self._lock:
            objs = self._objects.get(kind_cls.KIND, {})
            obj = objs.get((namespace, name))
            if obj is None:
                raise NotFoundError(f"{kind_cls.KIND} {namespace}/{name} not found")
        return self._read_clone(obj)

    def list(self, kind_cls: type, namespace: str | None = "default",
             selector: dict[str, str] | None = None,
             fields: dict[str, str] | None = None) -> list[Any]:
        with self._lock:
            self.list_scans += 1
            objs = self._objects.get(kind_cls.KIND, {})
            refs = [obj for (ns, _), obj in objs.items()
                    if (namespace is None or ns == namespace)
                    and matches_labels(obj, selector)
                    and matches_fields(obj, fields)]
        self._count_scan(kind_cls.KIND)
        out = [self._read_clone(o) for o in refs]
        out.sort(key=lambda o: o.meta.name)
        return out

    # ---- writes ----

    def create(self, obj: Any, actor: str = "system:grove-operator",
               epoch: int | None = None) -> Any:
        with self._locked_write("create"):
            kind = obj.KIND
            self._check_fence(kind, "create", epoch)
            objs = self._objects.setdefault(kind, {})
            key = _key(obj)
            if key in objs:
                raise AlreadyExistsError(f"{kind} {key[0]}/{key[1]} exists")
            stored = self._admit("create", clone(obj), None, actor)
            # Liveness check for controller owners: a create that races
            # its parent's cascade delete (reconciler read the parent,
            # cascade removed it, create lands after) would otherwise
            # insert a permanent orphan — nothing GCs an object whose
            # owner uid no longer exists. Creates and cascades both run
            # under this lock, so the check is exact, not best-effort.
            for ref in stored.meta.owner_references:
                if not ref.controller or not ref.uid:
                    continue
                owner = self._objects.get(ref.kind, {}).get(
                    (stored.meta.namespace, ref.name))
                if owner is None or owner.meta.uid != ref.uid:
                    raise NotFoundError(
                        f"owner {ref.kind} {stored.meta.namespace}/"
                        f"{ref.name} (uid {ref.uid}) is gone; refusing "
                        f"to create orphan {kind} {key[1]}")
            if not stored.meta.uid:
                stored.meta.uid = str(uuid.uuid4())
            if not stored.meta.creation_timestamp:
                stored.meta.creation_timestamp = time.time()
            # Lifecycle trace id: inherited from the object's own
            # annotation (controllers pre-stamp children with their
            # parent's id) or the creating span's context, minted fresh
            # otherwise — the Dapper-style root of the create→ready
            # trace every later pipeline stage appends spans to.
            GLOBAL_TRACER.ensure(stored.meta)
            stored.meta.resource_version = next(self._rv)
            stored.meta.generation = 1
            objs[key] = stored
            writeobs.note_commit(kind, "create")
            self._persist_put(stored)
            # The gang_created MILESTONE is recorded before the emit
            # (a scheduler binding off the ADDED event must find it
            # already present — its scheduled milestone anchors phase
            # deltas on it), but the hub OBSERVATION it closes is
            # deferred past lock release: the hub lock is held across
            # /metrics renders, and taking it here was the first
            # store→hub edge the GROVE_LOCKDEP witness recorded.
            observe = GLOBAL_TRACER.note_created(stored,
                                                 defer_observe=True)
            self._emit(EventType.ADDED, stored)
            out = clone(stored)
        if observe is not None:
            observe()
        return out

    def _get_live(self, obj: Any) -> Any:
        objs = self._objects.get(obj.KIND, {})
        live = objs.get(_key(obj))
        if live is None:
            ns, name = _key(obj)
            raise NotFoundError(f"{obj.KIND} {ns}/{name} not found")
        return live

    def update(self, obj: Any, actor: str = "system:grove-operator",
               epoch: int | None = None) -> Any:
        """Full update (spec+meta). Bumps generation when spec changed."""
        with self._locked_write("update"):
            self._check_fence(obj.KIND, "update", epoch)
            live = self._get_live(obj)
            if obj.meta.resource_version != live.meta.resource_version:
                writeobs.note_conflict(obj.KIND, "update")
                raise ConflictError(
                    f"{obj.KIND} {obj.meta.namespace}/{obj.meta.name}: stale "
                    f"resource_version {obj.meta.resource_version} != "
                    f"{live.meta.resource_version}")
            stored = self._admit("update", clone(obj), clone(live), actor)
            stored.meta.uid = live.meta.uid
            stored.meta.creation_timestamp = live.meta.creation_timestamp
            stored.meta.generation = live.meta.generation
            if hasattr(live, "spec") and to_dict(live.spec) != to_dict(stored.spec):
                stored.meta.generation += 1
            stored.meta.resource_version = next(self._rv)
            self._objects[obj.KIND][_key(obj)] = stored
            writeobs.note_commit(obj.KIND, "update")
            self._persist_put(stored)
            self._emit(EventType.MODIFIED, stored)
            if stored.meta.deletion_timestamp and not stored.meta.finalizers:
                self._remove(stored)
            return clone(stored)

    def update_status(self, obj: Any,
                      actor: str = "system:grove-operator",
                      epoch: int | None = None) -> Any:
        """Status-only update: ignores spec/meta edits in ``obj``.

        No-op writes (byte-identical status) are suppressed: reconcilers
        watch their own kinds and recompute status on every event, so
        un-suppressed no-op writes would self-trigger a reconcile hot loop
        at steady state.
        """
        with self._locked_write("update_status"):
            self._check_fence(obj.KIND, "update_status", epoch)
            stored = self._update_status_locked(obj, actor)
        # Return through the per-version bytes cache instead of a fresh
        # dumps+loads: every reconcile ends in a status write, and at
        # steady state the write is a suppressed no-op whose return
        # clone dominated the call (for real writes this also pre-warms
        # the new version's bytes for every subsequent reader).
        return self._read_clone(stored)

    def _update_status_locked(self, obj: Any, actor: str) -> Any:
        """Single source of truth for status-write semantics (shared by the
        singular and batched paths). Caller holds the lock."""
        live = self._get_live(obj)
        # Status is a privileged surface (node binding, breach conditions,
        # gang placement) — same authorization as spec. The defensive
        # clones exist only for the chain's benefit: skip them when no
        # chain is installed (they dominated the gang-bind write path).
        if self._admission is not None:
            self._admit("update_status", clone(obj), clone(live), actor)
        if obj.meta.resource_version != live.meta.resource_version:
            writeobs.note_conflict(obj.KIND, "update_status")
            raise ConflictError(
                f"{obj.KIND} {obj.meta.namespace}/{obj.meta.name}: stale "
                f"resource_version (status)")
        # Dataclass equality, not to_dict round-trips: statuses are
        # plain dataclasses (strs/numbers/lists/dicts/enums), where
        # field-wise __eq__ decides the same no-op question at a
        # fraction of the cost — this comparison runs on EVERY status
        # write, including each pod of a gang bind.
        if obj.status == live.status:
            writeobs.note_noop(obj.KIND)
            return live
        stored = clone(live)
        stored.status = clone(obj.status)
        stored.meta.resource_version = next(self._rv)
        self._objects[obj.KIND][_key(obj)] = stored
        writeobs.note_commit(obj.KIND, "update_status")
        self._persist_put(stored)
        self._emit(EventType.MODIFIED, stored)
        return stored

    def patch_status(self, kind_cls: type, name: str, patch: dict,
                     namespace: str = "default",
                     actor: str = "system:grove-operator",
                     epoch: int | None = None) -> Any:
        """Server-side status merge (the kubelet PATCH pattern —
        store/patch.py merge_status; conditions merge by type). No
        resource-version precondition: the read-modify-write happens
        atomically under the store lock, which is the consistency the
        optimistic-concurrency dance approximates from outside. This is
        what keeps a fleet of wire agents from conflict-looping against
        controllers that also write the same objects' status."""
        with self._locked_write("patch_status"):
            self._check_fence(kind_cls.KIND, "patch_status", epoch)
            stored = self._patch_status_locked(kind_cls, name, patch,
                                               namespace, actor)
        return self._read_clone(stored)  # as update_status: cached bytes

    def _patch_status_locked(self, kind_cls: type, name: str, patch: dict,
                             namespace: str, actor: str) -> Any:
        from grove_tpu.store.patch import merge_status
        live = self._objects.get(kind_cls.KIND, {}).get((namespace, name))
        if live is None:
            raise NotFoundError(
                f"{kind_cls.KIND} {namespace}/{name} not found")
        updated = clone(live)
        updated.status = merge_status(live.status, patch)
        if self._admission is not None:
            self._admit("update_status", clone(updated), clone(live), actor)
        if updated.status == live.status:
            writeobs.note_noop(kind_cls.KIND)
            return live                     # no-op suppression, as PUT
        updated.meta.resource_version = next(self._rv)
        self._objects[kind_cls.KIND][(namespace, name)] = updated
        writeobs.note_commit(kind_cls.KIND, "patch_status")
        self._persist_put(updated)
        self._emit(EventType.MODIFIED, updated)
        return updated

    def patch_status_many(self, kind_cls: type,
                          items: list[tuple[str, dict]],
                          namespace: str = "default",
                          actor: str = "system:grove-operator",
                          epoch: int | None = None
                          ) -> list[Exception | None]:
        """Batched status merge-patches under ONE lock acquisition — the
        wire twin of ``update_status_many`` (a kubelet fleet marking a
        gang's pods Ready writes hundreds of statuses at once; one
        locked batch lets watching controllers coalesce the burst into
        one reconcile instead of N). Returns one entry per item: None on
        success, NotFound/Validation/Forbidden otherwise — admission
        denials are per-item results, NOT a batch-level exception:
        earlier items have already committed and emitted by the time a
        later one is denied, so an exception here would report a
        partially-applied batch as total failure with no indication of
        which items landed."""
        from grove_tpu.runtime.errors import ForbiddenError
        results: list[Exception | None] = []
        with self._locked_write("patch_status"):
            # One fence check per batch (one writer, one epoch): a
            # deposed writer's whole batch is rejected before anything
            # commits — exactly the partial-batch ambiguity the
            # per-item result shape cannot express for fencing.
            self._check_fence(kind_cls.KIND, "patch_status", epoch)
            for name, patch in items:
                try:
                    self._patch_status_locked(kind_cls, name, patch,
                                              namespace, actor)
                    results.append(None)
                except (NotFoundError, ValidationError, ForbiddenError) as e:
                    results.append(e)
        return results

    def update_status_many(self, objs: list[Any],
                           actor: str = "system:grove-operator",
                           epoch: int | None = None
                           ) -> list[Exception | None]:
        """Batched status updates under one lock acquisition (the gang
        scheduler binds hundreds of pods at once; per-call locking and
        admission would serialise the bind against every reader).

        Returns one entry per input: None on success, NotFound/Conflict
        (the expected races) otherwise — callers decide per-object what a
        failure means. Any other exception (admission denial, codec bug)
        propagates loudly: swallowing it into the result list would turn
        a systemic failure into a silent forever-pending gang.
        """
        results: list[Exception | None] = []
        with self._locked_write("update_status"):
            if objs:    # one fence check per batch (see patch_status_many)
                self._check_fence(objs[0].KIND, "update_status", epoch)
            for obj in objs:
                try:
                    self._update_status_locked(obj, actor)
                    results.append(None)
                except (NotFoundError, ConflictError) as e:
                    results.append(e)
        return results

    def delete(self, kind_cls: type, name: str, namespace: str = "default",
               actor: str = "system:grove-operator",
               epoch: int | None = None) -> None:
        """Finalizer-aware delete: marks for deletion if finalizers remain,
        removes (and cascades to owned objects) otherwise."""
        with self._locked_write("delete"):
            self._check_fence(kind_cls.KIND, "delete", epoch)
            objs = self._objects.get(kind_cls.KIND, {})
            obj = objs.get((namespace, name))
            if obj is None:
                raise NotFoundError(f"{kind_cls.KIND} {namespace}/{name} not found")
            self._admit("delete", clone(obj), None, actor)
            if obj.meta.finalizers:
                if obj.meta.deletion_timestamp is None:
                    # Replace, never mutate in place (readers hold refs).
                    marked = clone(obj)
                    marked.meta.deletion_timestamp = time.time()
                    marked.meta.resource_version = next(self._rv)
                    self._objects[kind_cls.KIND][(namespace, name)] = marked
                    writeobs.note_commit(kind_cls.KIND, "delete")
                    self._persist_put(marked)
                    self._emit(EventType.MODIFIED, marked)
                return
            self._remove(obj)

    def _remove(self, obj: Any) -> None:
        """Unconditional removal + owner-reference cascade (GC analog)."""
        self._objects[obj.KIND].pop(_key(obj), None)
        self._clone_cache.pop(
            (obj.KIND, obj.meta.namespace, obj.meta.name), None)
        self._snapshot_cache.pop(
            (obj.KIND, obj.meta.namespace, obj.meta.name), None)
        writeobs.note_commit(obj.KIND, "delete")
        # Deletions get their own seq (kube bumps rv on delete too) so
        # resumable watches order them after the final MODIFIED; the
        # WAL delete record carries it so the warm-start tail scan can
        # rv-address every record.
        seq = next(self._rv)
        self._persist_delete(obj, rv=seq)
        self._emit(EventType.DELETED, obj, seq=seq)
        # Cascade: anything owned (controller ref) by this uid gets deleted.
        uid = obj.meta.uid
        dependents = [
            o for kind_objs in self._objects.values()
            for o in list(kind_objs.values())
            if any(ref.uid == uid for ref in o.meta.owner_references)
        ]
        for dep in dependents:
            if dep.meta.finalizers:
                if dep.meta.deletion_timestamp is None:
                    marked = clone(dep)
                    marked.meta.deletion_timestamp = time.time()
                    marked.meta.resource_version = next(self._rv)
                    self._objects[dep.KIND][_key(dep)] = marked
                    writeobs.note_commit(dep.KIND, "delete")
                    self._persist_put(marked)
                    self._emit(EventType.MODIFIED, marked)
            else:
                self._remove(dep)
