"""HTTP-backed client — the store-client surface over the wire.

Implements the same verbs as ``store.client.Client`` (get/list/create/
update_status/patch/delete) against a remote serve daemon's HTTP API,
with wire status codes mapped back to the typed error model (404 →
NotFoundError, 403 → ForbiddenError, 409 → ConflictError, 4xx →
GroveError). Anything that takes a ``Client`` and sticks to these verbs
— most importantly the ProcessKubelet and the startup barrier — runs
unchanged against a remote control plane, which is how one serve daemon
spans multiple hosts: each TPU host runs ``grovectl agent`` with an
HttpClient pinned to its node (see grove_tpu/agent docs and the
reference's in-pod initc, which likewise talks to the apiserver from
inside the workload boundary).

``watch_events`` is the wire informer feed: a blocking generator over
the server's resumable long-poll ``GET /watch`` (history-ring replay;
a gap raises ``WatchGoneError`` — relist and restart, kube semantics).
"""

from __future__ import annotations

import json
import os
from typing import Any
from urllib.parse import quote, urlencode

from grove_tpu.api.serde import from_dict, to_dict
from grove_tpu.runtime.errors import (
    ConflictError,
    ForbiddenError,
    GroveError,
    NotFoundError,
)


class WatchGoneError(GroveError):
    """The server's event history no longer covers the resume point;
    relist and start a fresh watch."""


# ---- fault injection (chaos harness + tests) ---------------------------
#
# The 410 gap path is the hardest watch code to reach organically: the
# server's history ring must wrap past a paused consumer's cursor. Both
# the chaos harness (chaos/faults.py WatchGapFault) and the wire tests
# need to force it deterministically; before this hook each did its own
# monkeypatching of ``watch_events``. ``arm_watch_gap`` is the ONE
# sanctioned injection point: the next N ``watch_events`` calls on the
# armed client raise WatchGoneError exactly where a real ring gap
# surfaces, so every consumer downstream (resumable_watch_events,
# Reflector, remote agents) exercises its genuine recovery path.
#
# Env-gated: arming is a no-op raise unless GROVE_FAULT_INJECT=1, so
# production code paths cannot trip it by accident — the flag is the
# explicit "this process runs chaos" opt-in.

FAULT_INJECT_ENV = "GROVE_FAULT_INJECT"


def fault_injection_enabled() -> bool:
    return os.environ.get(FAULT_INJECT_ENV, "") == "1"


def arm_watch_gap(client: "HttpClient", gaps: int = 1) -> None:
    """Arm ``client`` so its next ``gaps`` watch polls raise
    WatchGoneError (the injected history-ring gap). Requires
    GROVE_FAULT_INJECT=1 — refuses loudly otherwise so a stray call in
    a production process cannot silently degrade its watches."""
    if not fault_injection_enabled():
        raise RuntimeError(
            f"watch-gap injection requires {FAULT_INJECT_ENV}=1 "
            "(the chaos harness opt-in); refusing to arm")
    if gaps < 1:
        raise ValueError(f"gaps must be >= 1, got {gaps}")
    with client._gap_lock:
        client._armed_gaps += gaps


class HttpClient:
    def __init__(self, server: str, token: str = "", timeout: float = 10.0,
                 ca_file: str = ""):
        """``ca_file`` pins the server's CA for https:// endpoints (the
        self-managed cert manager's ca.crt, or the BYO CA). Without it,
        https uses the system trust store — which will reject the
        self-signed control-plane CA, by design."""
        self.server = server.rstrip("/")
        self.token = token
        self.timeout = timeout
        self.ca_file = ca_file
        self._ssl_ctx = None
        # Leadership fencing epoch (grove_tpu/ha): when set, every
        # mutating request carries X-Grove-Epoch so the leader's store
        # judges this writer's term (stale epoch -> 409 FencedError).
        # None = unfenced (ordinary clients).
        self.epoch: int | None = None
        # Leader-follow: a 503 whose body names the leader retries the
        # request there once (the standby's write redirect — clients
        # already retry on conflict; this is the HA analog). The hint
        # REPLACES self.server so subsequent requests go straight to
        # the leader.
        self.follow_leader = True
        # Armed fault-injection gaps (see arm_watch_gap): each
        # watch_events call consumes one and raises WatchGoneError.
        # Lock because arming (chaos thread) races consumption (the
        # watch consumer's thread) — an unsynchronized read-modify-
        # write could silently lose armed gaps.
        import threading
        self._gap_lock = threading.Lock()
        self._armed_gaps = 0

    # -- plumbing ---------------------------------------------------------

    def _context(self):
        import ssl

        if not self.server.startswith("https"):
            return None
        if self._ssl_ctx is None:
            self._ssl_ctx = ssl.create_default_context(
                cafile=self.ca_file or None)
        return self._ssl_ctx

    def _request(self, method: str, path: str, body: dict | None = None,
                 timeout: float | None = None, _followed: bool = False):
        import urllib.error
        import urllib.request

        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        if self.epoch is not None:
            headers["X-Grove-Epoch"] = str(self.epoch)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(f"{self.server}{path}", method=method,
                                     data=data, headers=headers)
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout,
                    context=self._context()) as resp:
                return json.loads(resp.read() or b"null")
        except urllib.error.HTTPError as e:
            raw = e.read()
            hint = ""
            try:
                decoded = json.loads(raw)
                msg = decoded.get("error", raw.decode(errors="replace"))
                hint = str(decoded.get("leader") or "")
            except (ValueError, AttributeError):
                msg = raw.decode(errors="replace")
            if e.code == 503 and hint and self.follow_leader \
                    and not _followed and hint.rstrip("/") != self.server:
                # Standby redirect: re-target the leader and retry ONCE
                # (a hint chain longer than one hop means split-brain
                # confusion worth surfacing, not chasing).
                self.server = hint.rstrip("/")
                self._ssl_ctx = None    # scheme/CA may differ per host
                return self._request(method, path, body, timeout,
                                     _followed=True)
            if e.code == 404:
                raise NotFoundError(msg)
            if e.code == 403:
                raise ForbiddenError(msg)
            if e.code == 409:
                raise ConflictError(msg)
            if e.code == 410:
                raise WatchGoneError(msg)
            if e.code == 401:
                raise ForbiddenError(f"unauthenticated: {msg}")
            raise GroveError(msg)
        except urllib.error.URLError as e:
            raise GroveError(f"cannot reach {self.server}: {e.reason}")
        except (OSError, ValueError) as e:
            # Mid-read failures (reset/timeout during resp.read(), or a
            # truncated JSON body) are neither HTTPError nor URLError;
            # unwrapped they'd kill callers' retry loops — the remote
            # agent's watch thread only handles GroveError.
            raise GroveError(f"request to {self.server} failed "
                             f"mid-response: {e}")

    # -- verbs ------------------------------------------------------------

    def get(self, kind_cls: type, name: str,
            namespace: str = "default") -> Any:
        data = self._request(
            "GET", f"/api/{kind_cls.KIND}/{quote(name)}"
                   f"?{urlencode({'namespace': namespace})}")
        return from_dict(kind_cls, data)

    def list(self, kind_cls: type, namespace: str | None = "default",
             selector: dict[str, str] | None = None,
             fields: dict[str, str] | None = None) -> list[Any]:
        """``fields`` filters on STATUS fields server-side (the kube
        fieldSelector analog; values may be comma-separated ORs) — the
        server filters before serializing, so an agent fleet's polls
        don't make it serialize the whole cluster per request."""
        params = {"namespace": namespace if namespace is not None else "*"}
        for k, v in (selector or {}).items():
            params[f"l.{k}"] = v
        for k, v in (fields or {}).items():
            params[f"f.{k}"] = v
        data = self._request(
            "GET", f"/api/{kind_cls.KIND}?{urlencode(params)}")
        return [from_dict(kind_cls, d) for d in data]

    def current_rv(self) -> int:
        """The server's highest resource version (one GET /watch
        bootstrap round trip) — the wire twin of Client.current_rv, so
        read-mostly consumers can run the same is-my-snapshot-fresh
        check against a remote control plane. There is no wire
        list_snapshot: HTTP readers deserialize per request anyway, so
        the shared-clone optimisation has nothing to share."""
        return int(self._request("GET", "/watch")["rv"])

    def create(self, obj: Any) -> Any:
        doc = {"kind": obj.KIND,
               "metadata": {"name": obj.meta.name,
                            "namespace": obj.meta.namespace,
                            "labels": dict(obj.meta.labels),
                            "annotations": dict(obj.meta.annotations)}}
        if hasattr(obj, "spec"):
            doc["spec"] = to_dict(obj.spec)
        results = self._request("POST", "/apply", doc)
        action = results[0].get("action") if results else None
        if action == "forbidden":
            raise ForbiddenError(results[0].get("error", "forbidden"))
        return self.get(type(obj), obj.meta.name, obj.meta.namespace)

    def update_status(self, obj: Any) -> Any:
        data = self._request(
            "PUT", f"/api/{obj.KIND}/{quote(obj.meta.name)}/status",
            to_dict(obj))
        return from_dict(type(obj), data)

    def patch(self, kind_cls: type, name: str, patch: dict,
              namespace: str = "default") -> Any:
        data = self._request(
            "PATCH", f"/api/{kind_cls.KIND}/{quote(name)}"
                     f"?{urlencode({'namespace': namespace})}", patch)
        return from_dict(kind_cls, data)

    def patch_status_many(self, kind_cls: type,
                          items: list[tuple[str, dict]],
                          namespace: str = "default"
                          ) -> list[Exception | None]:
        """Batched status merge patches in ONE round trip (the server
        applies them under one store lock — POST /batch/<kind>/status).
        Returns one entry per item: None or GroveError."""
        data = self._request(
            "POST", f"/batch/{kind_cls.KIND}/status",
            {"namespace": namespace,
             "items": [{"name": n, "patch": p} for n, p in items]})
        return [None if r is None else GroveError(r["error"])
                for r in data["results"]]

    def patch_status(self, kind_cls: type, name: str, patch: dict,
                     namespace: str = "default") -> Any:
        """Status-subresource merge patch: one round trip, no read, no
        rv conflict (the server merges under its lock; conditions merge
        by type). The kubelet status-write pattern — what lets a fleet
        of agents write readiness without conflict-looping against
        controllers."""
        data = self._request(
            "PATCH", f"/api/{kind_cls.KIND}/{quote(name)}/status"
                     f"?{urlencode({'namespace': namespace})}", patch)
        return from_dict(kind_cls, data)

    def delete(self, kind_cls: type, name: str,
               namespace: str = "default") -> None:
        self._request("DELETE", f"/api/{kind_cls.KIND}/{quote(name)}"
                                f"?{urlencode({'namespace': namespace})}")

    def debug_traces(self, trace_id: str | None = None) -> dict:
        """Lifecycle-trace dump from ``GET /debug/traces`` (the wire
        twin of ``Client.debug_traces``; requires profiling.enabled on
        the server — 404 maps to NotFoundError)."""
        path = "/debug/traces"
        if trace_id:
            path += f"?{urlencode({'trace_id': trace_id})}"
        return self._request("GET", path)

    def debug_placement(self, name: str,
                        namespace: str = "default") -> dict:
        """One PodGang's raw placement diagnosis from
        ``GET /debug/placement/<ns>/<name>`` (the wire twin of
        ``Client.debug_placement``; 404 maps to NotFoundError)."""
        return self._request(
            "GET", f"/debug/placement/{quote(namespace)}/{quote(name)}")

    def debug_deploy(self, name: str, namespace: str = "default") -> dict:
        """One PodCliqueSet's deploy-progress record from
        ``GET /debug/deploy/<ns>/<name>`` (the wire twin of
        ``Client.debug_deploy``; 404 maps to NotFoundError)."""
        return self._request(
            "GET", f"/debug/deploy/{quote(namespace)}/{quote(name)}")

    def debug_serving(self, name: str, namespace: str = "default") -> dict:
        """One serving scope's SLO state from
        ``GET /debug/serving/<ns>/<name>`` (the wire twin of
        ``Client.debug_serving``; 404 maps to NotFoundError)."""
        return self._request(
            "GET", f"/debug/serving/{quote(namespace)}/{quote(name)}")

    def debug_xprof(self, name: str, namespace: str = "default") -> dict:
        """One engine's data-plane observatory payload from
        ``GET /debug/xprof/<ns>/<name>`` (the wire twin of
        ``Client.debug_xprof``; 404 maps to NotFoundError)."""
        return self._request(
            "GET", f"/debug/xprof/{quote(namespace)}/{quote(name)}")

    def debug_requests(self, name: str,
                       namespace: str = "default") -> dict:
        """One engine's request-observatory payload from
        ``GET /debug/requests/<ns>/<name>`` (the wire twin of
        ``Client.debug_requests``; 404 maps to NotFoundError)."""
        return self._request(
            "GET", f"/debug/requests/{quote(namespace)}/{quote(name)}")

    def debug_defrag(self) -> dict:
        """The defrag plan ledger from ``GET /debug/defrag`` (the wire
        twin of ``Client.debug_defrag``; 404 maps to NotFoundError)."""
        return self._request("GET", "/debug/defrag")

    def debug_disruption(self) -> dict:
        """The disruption-contract ledger from ``GET /debug/disruption``
        (the wire twin of ``Client.debug_disruption``; 404 maps to
        NotFoundError)."""
        return self._request("GET", "/debug/disruption")

    def debug_leadership(self) -> dict:
        """This replica's leadership view from ``GET /debug/leadership``
        (the wire twin of ``Client.debug_leadership``; grovectl
        leader-status renders either)."""
        return self._request("GET", "/debug/leadership")

    def debug_controlplane(self) -> dict:
        """The control-plane observatory's sweep ledger from
        ``GET /debug/controlplane`` (the wire twin of
        ``Client.debug_controlplane``; grovectl controlplane-status
        renders either; 404 maps to NotFoundError)."""
        return self._request("GET", "/debug/controlplane")

    def watch_events(self, kinds: list[str] | None = None,
                     namespace: str | None = None,
                     selector: dict[str, str] | None = None,
                     since: int | None = None,
                     poll_timeout: float = 25.0,
                     with_ts: bool = False):
        """Blocking generator of (seq, type_str, obj) from the server's
        event feed — (seq, type_str, obj, emit_ts) with ``with_ts``
        (wire informers feed the event-lag histogram from it; emit_ts
        is 0.0 against servers that predate the field). ``since=None``
        bootstraps at the current rv (only NEW events flow). Raises
        WatchGoneError when the server's history no longer covers the
        resume point."""
        from grove_tpu.manifest import KIND_REGISTRY

        if since is None:
            since = self._request("GET", "/watch")["rv"]
        params: dict[str, str] = {"since": str(since),
                                  "timeout": str(poll_timeout)}
        if kinds:
            params["kinds"] = ",".join(kinds)
        params["namespace"] = namespace if namespace is not None else "*"
        for k, v in (selector or {}).items():
            params[f"l.{k}"] = v
        while True:
            # Injected history-ring gap (arm_watch_gap), checked PER
            # POLL: a long-lived consumer (the Reflector holds one
            # generator for its whole life) must see a gap armed
            # mid-stream on its next poll round — exactly where a real
            # server 410 surfaces — not only at generator creation.
            with self._gap_lock:
                fire = self._armed_gaps > 0
                if fire:
                    self._armed_gaps -= 1
            if fire:
                raise WatchGoneError("injected watch gap (fault hook)")
            params["since"] = str(since)
            resp = self._request(
                "GET", f"/watch?{urlencode(params)}",
                timeout=poll_timeout + 5.0)
            for ev in resp["events"]:
                cls = KIND_REGISTRY.get(ev["kind"])
                if cls is None:
                    continue
                obj = from_dict(cls, ev["object"])
                if with_ts:
                    yield (ev["seq"], ev["type"], obj,
                           float(ev.get("ts", 0.0)))
                else:
                    yield ev["seq"], ev["type"], obj
            since = resp["rv"]


def resumable_watch_events(client: HttpClient,
                           kinds: list[str] | None = None,
                           namespace: str | None = None,
                           selector: dict[str, str] | None = None,
                           poll_timeout: float = 25.0,
                           on_gap=None,
                           on_error=None,
                           stop=None,
                           retry_wait: float = 1.0,
                           with_ts: bool = False,
                           since: int | None = None):
    """``watch_events`` that never dies: the shared relist-and-resume
    loop every wire watch consumer needs (remote agents, wire
    informers, the relay).

    - A history-ring gap (``WatchGoneError``) calls ``on_gap()`` — the
      consumer must re-seed whatever it derives from the stream (re-list
      a cache, wake a re-listing kubelet) because the missed events are
      unrecoverable. If ``on_gap`` returns an int, the watch resumes
      from that seq (return the re-list's rv and the reseed-to-resume
      window is covered by replay — no blind gap); otherwise it
      re-bootstraps at the server's current rv.
    - Transport errors call ``on_error(exc)`` (log it there) and retry
      after ``retry_wait`` seconds.
    - ``stop`` (a threading.Event) ends the generator; it is also used
      for interruptible retry sleeps, so a stopping consumer never
      blocks on the backoff.

    ``since`` anchors the FIRST watch (pass the seed list's rv so
    writes landing between that list and the watch connecting are
    replayed, not skipped — the same no-blind-window contract the gap
    path honors); None bootstraps at the server's current rv.

    Yields exactly what ``watch_events`` does — (seq, type_str, obj),
    or with the emit timestamp appended under ``with_ts``.
    """
    import time as _time

    while stop is None or not stop.is_set():
        try:
            for item in client.watch_events(
                    kinds, namespace, selector, since=since,
                    poll_timeout=poll_timeout, with_ts=with_ts):
                yield item
                since = item[0]
                if stop is not None and stop.is_set():
                    return
            return  # watch_events only returns on its own when exhausted
        except WatchGoneError:
            # The resume point predates the server's ring: events were
            # lost for good. Re-seed derived state; a reseed that
            # reports its rv anchors the resume there (covering the
            # reseed-to-resume window), else restart at the current rv
            # (since=None bootstraps).
            since = None
            if on_gap is not None:
                resumed = on_gap()
                if isinstance(resumed, int):
                    since = resumed
            # A persistent gap (churn outruns the server's ring every
            # round trip) must not spin full relists at line rate
            # against an already-loaded server: pace the resume like
            # any other retry.
            if stop is not None:
                stop.wait(retry_wait)
            else:
                _time.sleep(retry_wait)
        except GroveError as e:
            if on_error is not None:
                on_error(e)
            if stop is not None:
                stop.wait(retry_wait)
            else:
                _time.sleep(retry_wait)
