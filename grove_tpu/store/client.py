"""Typed client + error-injecting fake.

The real client is a thin veneer over the Store (one process, no wire
format). ``FakeClient`` mirrors the reference's TestClientBuilder
(operator/test/utils/client.go:36-58): record errors per (method, kind,
name) and they are replayed to the caller, so reconcilers are exercised
against apiserver failure modes without special hooks in production code.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Iterable

from grove_tpu.store.store import Store, Watcher


class Client:
    def __init__(self, store: Store, actor: str = "system:grove-operator"):
        self._store = store
        self.actor = actor
        # Leadership fencing epoch (grove_tpu/ha): stamped by the
        # Manager on the control plane's own writers at promotion so a
        # deposed leader's straggler writes are rejected by the store
        # (FencedError) instead of racing the new leader. None = an
        # unfenced writer (users, node agents) — never gated.
        self.epoch: int | None = None

    def impersonate(self, actor: str) -> "Client":
        """A client acting as a different principal (authorization tests,
        user-facing surfaces). The impersonated client is UNFENCED
        (epoch None): wire-user writes are gated by the server's
        leadership check, not the writer epoch."""
        return Client(self._store, actor)

    def get(self, kind_cls: type, name: str, namespace: str = "default") -> Any:
        return self._store.get(kind_cls, name, namespace)

    def list(self, kind_cls: type, namespace: str | None = "default",
             selector: dict[str, str] | None = None,
             fields: dict[str, str] | None = None) -> list[Any]:
        return self._store.list(kind_cls, namespace, selector, fields)

    def list_snapshot(self, kind_cls: type,
                      namespace: str | None = "default",
                      selector: dict[str, str] | None = None
                      ) -> tuple[int, list[Any]]:
        """Read-only shared-object list + the store rv it was taken at
        (see Store.list_snapshot for the no-mutation contract)."""
        return self._store.list_snapshot(kind_cls, namespace, selector)

    def current_rv(self) -> int:
        """Highest resource version the store has issued — lets a
        read-mostly consumer (the placement snapshot) cheaply detect
        whether the world moved since its last read."""
        return self._store.current_rv()

    def create(self, obj: Any) -> Any:
        return self._store.create(obj, actor=self.actor, epoch=self.epoch)

    def dry_run_admit(self, obj: Any) -> str:
        return self._store.dry_run_admit(obj, actor=self.actor)

    def update(self, obj: Any) -> Any:
        return self._store.update(obj, actor=self.actor, epoch=self.epoch)

    def update_status(self, obj: Any) -> Any:
        return self._store.update_status(obj, actor=self.actor,
                                         epoch=self.epoch)

    def update_status_many(self, objs: list[Any]) -> list[Exception | None]:
        return self._store.update_status_many(objs, actor=self.actor,
                                              epoch=self.epoch)

    def patch_status(self, kind_cls: type, name: str, patch: dict,
                     namespace: str = "default") -> Any:
        """Status-subresource merge patch (conditions merge by type; no
        rv precondition — see Store.patch_status)."""
        return self._store.patch_status(kind_cls, name, patch, namespace,
                                        actor=self.actor, epoch=self.epoch)

    def patch_status_many(self, kind_cls: type,
                          items: list[tuple[str, dict]],
                          namespace: str = "default"
                          ) -> list[Exception | None]:
        return self._store.patch_status_many(kind_cls, items, namespace,
                                             actor=self.actor,
                                             epoch=self.epoch)

    def delete(self, kind_cls: type, name: str, namespace: str = "default") -> None:
        return self._store.delete(kind_cls, name, namespace,
                                  actor=self.actor, epoch=self.epoch)

    def patch(self, kind_cls: type, name: str, patch: dict,
              namespace: str = "default", retries: int = 3) -> Any:
        """JSON-merge-patch (RFC 7386) against spec/labels/annotations
        with a bounded optimistic-concurrency retry (the client-go
        MergeFrom analog — see store/patch.py)."""
        from grove_tpu.runtime.errors import ConflictError, FencedError
        from grove_tpu.store.patch import apply_patch
        last: Exception | None = None
        for _ in range(max(1, retries)):
            live = self.get(kind_cls, name, namespace)
            try:
                return self.update(apply_patch(live, patch))
            except FencedError:
                # Terminal: the epoch only moves forward, so re-reading
                # and retrying a fenced write is guaranteed identical
                # failure — stand down immediately.
                raise
            except ConflictError as e:  # raced a writer; re-read and retry
                last = e
        raise last

    def watch(self, kinds: Iterable[str] | None = None,
              selector: dict[str, str] | None = None) -> Watcher:
        return self._store.watch(kinds, selector)

    def debug_traces(self, trace_id: str | None = None) -> dict:
        """Raw lifecycle-trace dump ({"spans", "milestones", "starts"})
        — the in-process twin of ``GET /debug/traces``, so tests and
        tooling read one shape against either client surface."""
        from grove_tpu.runtime.trace import GLOBAL_TRACER
        return GLOBAL_TRACER.export(trace_id)

    def debug_placement(self, name: str,
                        namespace: str = "default") -> dict:
        """One PodGang's raw placement diagnosis — the in-process twin
        of ``GET /debug/placement/<ns>/<name>`` (same payload shape;
        grovectl explain renders either)."""
        from grove_tpu.api import PodGang
        from grove_tpu.scheduler.explain import placement_payload
        return placement_payload(self.get(PodGang, name, namespace))

    def debug_deploy(self, name: str, namespace: str = "default") -> dict:
        """One PodCliqueSet's deploy-progress record — the in-process
        twin of ``GET /debug/deploy/<ns>/<name>`` (same payload shape;
        grovectl deploy-status renders either). Raises NotFoundError
        when no observatory runs on this store or the PCS predates it."""
        from grove_tpu.runtime.deploywatch import observer_for
        from grove_tpu.runtime.errors import NotFoundError
        obs = observer_for(self._store)
        if obs is None:
            raise NotFoundError(
                "deploy observatory is not running for this store "
                "(no started Manager owns it)")
        payload = obs.payload(namespace, name)
        if payload is None:
            raise NotFoundError(
                f"no deploy record for PodCliqueSet {namespace}/{name} "
                "(created before the observatory started, or evicted)")
        return payload

    def debug_defrag(self) -> dict:
        """The defrag controller's plan ledger — the in-process twin of
        ``GET /debug/defrag`` (same payload shape; grovectl
        defrag-status renders either). Raises NotFoundError when no
        defrag controller runs on this store (defrag.enabled=False)."""
        from grove_tpu.defrag import defrag_for
        from grove_tpu.runtime.errors import NotFoundError
        dc = defrag_for(self._store)
        if dc is None:
            raise NotFoundError(
                "defrag controller is not running for this store "
                "(no started Manager owns it, or defrag.enabled=False)")
        return dc.payload()

    def debug_disruption(self) -> dict:
        """The disruption-contract ledger — the in-process twin of
        ``GET /debug/disruption`` (same payload shape; grovectl
        disruptions renders either). Raises NotFoundError when no
        reclaim controller runs on this store
        (disruption.enabled=False)."""
        from grove_tpu.disruption.reclaim import reclaim_for
        from grove_tpu.runtime.errors import NotFoundError
        rc = reclaim_for(self._store)
        if rc is None:
            raise NotFoundError(
                "reclaim controller is not running for this store "
                "(no started Manager owns it, or disruption.enabled="
                "False)")
        return rc.payload()

    def debug_leadership(self) -> dict:
        """This replica's leadership view — the in-process twin of
        ``GET /debug/leadership`` (same payload shape; grovectl
        leader-status renders either). Raises NotFoundError when no
        started Manager owns this store."""
        from grove_tpu.ha.election import leadership_for
        from grove_tpu.runtime.errors import NotFoundError
        ls = leadership_for(self._store)
        if ls is None:
            raise NotFoundError(
                "no leadership state for this store "
                "(no started Manager owns it)")
        return ls.payload(self._store)

    def debug_xprof(self, name: str, namespace: str = "default") -> dict:
        """One engine's data-plane observatory payload (compile table,
        phase breakdown, memory accounting, roofline estimates) — the
        in-process twin of ``GET /debug/xprof/<ns>/<name>`` (same
        payload shape; grovectl engine-profile renders either). Raises
        NotFoundError when no observatory is registered under the
        scope in this process (engine not running here, or
        GROVE_XPROF=0)."""
        from grove_tpu.runtime.errors import NotFoundError
        from grove_tpu.serving import xprof
        obs = xprof.observatory_for(name, namespace)
        if obs is None:
            known = ", ".join(f"{ns}/{n}" for ns, n in xprof.scopes()) \
                or "none"
            raise NotFoundError(
                f"no xprof observatory registered for {namespace}/{name} "
                f"in this process (GROVE_XPROF=0, or the engine runs "
                f"elsewhere; registered: {known})")
        return obs.payload()

    def debug_requests(self, name: str,
                       namespace: str = "default") -> dict:
        """One engine's request-observatory payload (finished-trace
        ring, slowest-K, per-phase p99 attribution) — the in-process
        twin of ``GET /debug/requests/<ns>/<name>`` (same payload
        shape; grovectl request-trace renders either). Raises
        NotFoundError when no recorder is registered under the scope
        in this process (engine not running here, or
        GROVE_REQTRACE=0)."""
        from grove_tpu.runtime.errors import NotFoundError
        from grove_tpu.serving import reqtrace
        rec = reqtrace.recorder_for(name, namespace)
        if rec is None:
            known = ", ".join(f"{ns}/{n}"
                              for ns, n in reqtrace.scopes()) or "none"
            raise NotFoundError(
                f"no request recorder registered for "
                f"{namespace}/{name} in this process (GROVE_REQTRACE=0,"
                f" or the engine runs elsewhere; registered: {known})")
        return rec.payload()

    def debug_serving(self, name: str, namespace: str = "default") -> dict:
        """One serving scope's SLO state — the in-process twin of
        ``GET /debug/serving/<ns>/<name>`` (same payload shape;
        grovectl serving-status renders either). Raises NotFoundError
        when no serving observatory runs on this store or no engine
        has reported fresh samples for the scope."""
        from grove_tpu.runtime.errors import NotFoundError
        from grove_tpu.runtime.servingwatch import serving_observer_for
        obs = serving_observer_for(self._store)
        if obs is None:
            raise NotFoundError(
                "serving observatory is not running for this store "
                "(no started Manager owns it, or the autoscaler is "
                "disabled)")
        payload = obs.payload(namespace, name)
        if payload is None:
            raise NotFoundError(
                f"no fresh serving samples for {namespace}/{name} "
                "(no engine reported inside the sample TTL)")
        return payload

    def debug_controlplane(self) -> dict:
        """The control-plane observatory's sweep ledger (per-controller
        reconcile attribution, write-amplification, watch-lag SLO) —
        the in-process twin of ``GET /debug/controlplane`` (same
        payload shape; grovectl controlplane-status renders either).
        Raises NotFoundError when no observatory runs on this store."""
        from grove_tpu.runtime.errors import NotFoundError
        from grove_tpu.runtime.sweepobs import observer_for
        obs = observer_for(self._store)
        if obs is None:
            raise NotFoundError(
                "control-plane observatory is not running for this "
                "store (no started Manager owns it)")
        return obs.payload()


@dataclasses.dataclass
class _InjectedError:
    method: str                 # get/list/create/update/update_status/delete
    error: Exception
    kind: str | None = None     # None = any kind
    name: str | None = None     # None = any object
    times: int = 1              # how many calls it poisons (-1 = forever)


class FakeClient(Client):
    """Client with scripted error injection and call recording."""

    def __init__(self, store: Store | None = None):
        super().__init__(store or Store())
        self._errors: list[_InjectedError] = []
        self._calls: list[tuple[str, str, str]] = []  # (method, kind, name)
        self._lock = threading.Lock()

    @property
    def store(self) -> Store:
        return self._store

    def inject_error(self, method: str, error: Exception, kind: str | None = None,
                     name: str | None = None, times: int = 1) -> None:
        with self._lock:
            self._errors.append(_InjectedError(method, error, kind, name, times))

    def calls(self, method: str | None = None) -> list[tuple[str, str, str]]:
        with self._lock:
            return [c for c in self._calls if method is None or c[0] == method]

    def _intercept(self, method: str, kind: str, name: str) -> None:
        with self._lock:
            self._calls.append((method, kind, name))
            for inj in self._errors:
                if inj.method != method:
                    continue
                if inj.kind is not None and inj.kind != kind:
                    continue
                if inj.name is not None and inj.name != name:
                    continue
                if inj.times == 0:
                    continue
                if inj.times > 0:
                    inj.times -= 1
                raise inj.error

    def get(self, kind_cls: type, name: str, namespace: str = "default") -> Any:
        self._intercept("get", kind_cls.KIND, name)
        return super().get(kind_cls, name, namespace)

    def list(self, kind_cls: type, namespace: str | None = "default",
             selector: dict[str, str] | None = None,
             fields: dict[str, str] | None = None) -> list[Any]:
        self._intercept("list", kind_cls.KIND, "")
        return super().list(kind_cls, namespace, selector, fields)

    def list_snapshot(self, kind_cls: type,
                      namespace: str | None = "default",
                      selector: dict[str, str] | None = None
                      ) -> tuple[int, list[Any]]:
        # Recorded (and poisoned) as "list": the snapshot path is a
        # list-shaped read, and scripted list failures should exercise
        # consumers regardless of which read path they take.
        self._intercept("list", kind_cls.KIND, "")
        return super().list_snapshot(kind_cls, namespace, selector)

    def create(self, obj: Any) -> Any:
        self._intercept("create", obj.KIND, obj.meta.name)
        return super().create(obj)

    def update(self, obj: Any) -> Any:
        self._intercept("update", obj.KIND, obj.meta.name)
        return super().update(obj)

    def update_status(self, obj: Any) -> Any:
        self._intercept("update_status", obj.KIND, obj.meta.name)
        return super().update_status(obj)

    def update_status_many(self, objs: list[Any]) -> list[Exception | None]:
        # Batches decompose to singular writes so injected update_status
        # errors replay and every call is recorded (the whole point of
        # this fake); production batching is a store-level optimisation.
        from grove_tpu.runtime.errors import ConflictError, NotFoundError
        results: list[Exception | None] = []
        for obj in objs:
            try:
                self.update_status(obj)
                results.append(None)
            except (NotFoundError, ConflictError) as e:
                results.append(e)
        return results

    def patch(self, kind_cls: type, name: str, patch: dict,
              namespace: str = "default", retries: int = 3) -> Any:
        # Recorded as its own verb; the get/update it decomposes into
        # are ALSO recorded and injectable — patch retry behavior is
        # exactly what failure-injection tests want to poke.
        self._intercept("patch", kind_cls.KIND, name)
        return super().patch(kind_cls, name, patch, namespace, retries)

    def patch_status(self, kind_cls: type, name: str, patch: dict,
                     namespace: str = "default") -> Any:
        self._intercept("patch_status", kind_cls.KIND, name)
        return super().patch_status(kind_cls, name, patch, namespace)

    def patch_status_many(self, kind_cls: type,
                          items: list[tuple[str, dict]],
                          namespace: str = "default"
                          ) -> list[Exception | None]:
        # Decomposed like update_status_many: injected patch_status
        # errors replay per item and every call is recorded.
        from grove_tpu.runtime.errors import (
            ForbiddenError,
            NotFoundError,
            ValidationError,
        )
        results: list[Exception | None] = []
        for name, patch in items:
            try:
                self.patch_status(kind_cls, name, patch, namespace)
                results.append(None)
            except (NotFoundError, ValidationError, ForbiddenError) as e:
                results.append(e)
        return results

    def delete(self, kind_cls: type, name: str, namespace: str = "default") -> None:
        self._intercept("delete", kind_cls.KIND, name)
        return super().delete(kind_cls, name, namespace)
