"""JSON-merge-patch support — the reference's patch/apply-helper analog.

Reference R8 (operator/internal/utils/kubernetes/) wraps client-go's
patch machinery (MergeFrom / server-side apply) so controllers and
tooling can mutate a narrow slice of an object without round-tripping
the whole spec through read-modify-write conflicts. Here the analog is:

- ``json_merge_patch`` — RFC 7386 on plain data: dicts merge
  recursively, ``null`` deletes a key, everything else replaces.
- ``apply_patch`` — apply a merge patch to a typed API object's
  mutable surface (``spec`` + ``metadata.labels``/``annotations``);
  identity/system fields (name, uid, resourceVersion, status…) are
  rejected, mirroring what the apiserver refuses or what belongs to the
  status subresource.
- ``Client.patch`` (store/client.py) — get → apply → update with a
  bounded optimistic-concurrency retry, so callers patch without
  holding a fresh read. Exposed on the wire as
  ``PATCH /api/<kind>/<name>`` and as ``grovectl patch``.
"""

from __future__ import annotations

import copy
from typing import Any

from grove_tpu.api.serde import from_dict, to_dict, type_problems
from grove_tpu.runtime.errors import ValidationError

# metadata keys a patch may touch; everything else in metadata is
# identity/bookkeeping owned by the store.
_PATCHABLE_META = {"labels", "annotations"}


def json_merge_patch(target: Any, patch: Any) -> Any:
    """RFC 7386: returns the patched copy of ``target``."""
    if not isinstance(patch, dict):
        return copy.deepcopy(patch)
    result = dict(target) if isinstance(target, dict) else {}
    for key, value in patch.items():
        if value is None:
            result.pop(key, None)
        else:
            result[key] = json_merge_patch(result.get(key), value)
    return result


def apply_patch(obj: Any, patch: dict) -> Any:
    """Apply a merge patch to a typed object; returns a new object.

    Allowed top-level keys: ``spec`` and ``metadata`` (labels /
    annotations only). Unknown or immutable keys raise ValidationError —
    a patch that silently ignored half its content would be worse than
    one that fails."""
    if not isinstance(patch, dict):
        raise ValidationError("patch must be a JSON object")
    allowed = {"spec", "metadata"}
    unknown = set(patch) - allowed
    if unknown:
        raise ValidationError(
            f"patch keys {sorted(unknown)} not patchable "
            f"(allowed: {sorted(allowed)}; status has no patch surface)")
    meta_patch = patch.get("metadata", {})
    if not isinstance(meta_patch, dict):
        raise ValidationError("patch metadata must be a JSON object")
    bad_meta = set(meta_patch) - _PATCHABLE_META
    if bad_meta:
        raise ValidationError(
            f"metadata keys {sorted(bad_meta)} not patchable "
            f"(allowed: {sorted(_PATCHABLE_META)})")

    cls = type(obj)
    data = to_dict(obj)
    if "spec" in patch:
        data["spec"] = json_merge_patch(data.get("spec"), patch["spec"])
    for key in _PATCHABLE_META & set(meta_patch):
        data["meta"][key] = json_merge_patch(
            data["meta"].get(key), meta_patch[key])
    try:
        patched = from_dict(cls, data)
    except (TypeError, ValueError, KeyError) as e:
        raise ValidationError(f"patch does not fit {cls.KIND} schema: {e}")
    problems = type_problems(patched)
    if problems:
        raise ValidationError(
            f"patch does not fit {cls.KIND} schema: " + "; ".join(problems))
    return patched


def merge_status(status_obj: Any, patch: dict) -> Any:
    """Apply a merge patch to a typed status object — the
    status-subresource counterpart of ``apply_patch`` (the kubelet
    PATCHes pod status; reference R8's client-go Status().Patch()).

    RFC 7386 semantics, with one strategic-merge extension mirroring
    upstream kube: a ``conditions`` list merges BY ``type`` (the
    patchMergeKey on every k8s conditions field) instead of being
    replaced wholesale — a writer updating Ready must not clobber the
    Scheduled condition another controller owns. A condition entry of
    ``null`` body deletes that type.
    """
    if not isinstance(patch, dict):
        raise ValidationError("status patch must be a JSON object")
    cls = type(status_obj)
    data = to_dict(status_obj)
    cond_patch = patch.get("conditions")
    rest = {k: v for k, v in patch.items() if k != "conditions"}
    merged = json_merge_patch(data, rest)
    if cond_patch is not None:
        if not isinstance(cond_patch, list):
            raise ValidationError("status patch conditions must be a list")
        by_type = {c.get("type"): dict(c)
                   for c in data.get("conditions") or []}
        for entry in cond_patch:
            if not isinstance(entry, dict) or "type" not in entry:
                raise ValidationError(
                    "each conditions patch entry needs a 'type'")
            others = {k: v for k, v in entry.items() if k != "type"}
            if others and all(v is None for v in others.values()):
                by_type.pop(entry["type"], None)   # explicit-null delete
            else:
                old = by_type.get(entry["type"], {})
                new = json_merge_patch(old, entry)
                # Condition-timestamp invariant (api/meta.set_condition):
                # last_transition_time stamps when ``status`` last
                # CHANGED. Wire writers don't supply it, so the merge
                # must maintain it — otherwise a condition patched over
                # the wire carries 0.0/stale and every transition-age
                # reader (e.g. breach_started_at in replica_lifecycle)
                # sees "breached since epoch" → instant gang
                # termination.
                if entry.get("last_transition_time") is None:
                    import time as _time
                    # A type not previously present is a NEW condition:
                    # stamped now even if the patch omitted 'status'
                    # (set_condition stamps every new condition; a 0.0
                    # default here would read as 'since epoch').
                    if not old or old.get("status") != new.get("status"):
                        new["last_transition_time"] = _time.time()
                    else:
                        new["last_transition_time"] = \
                            old.get("last_transition_time", 0.0)
                by_type[entry["type"]] = new
        merged["conditions"] = list(by_type.values())
    try:
        patched = from_dict(cls, merged)
    except (TypeError, ValueError, KeyError) as e:
        raise ValidationError(f"status patch does not fit "
                              f"{cls.__name__}: {e}")
    return patched
