"""Store persistence: WAL + snapshot — the etcd-durability analog.

The reference keeps every scrap of control-plane state in etcd, which is
why operator restart is free (SURVEY.md §5 checkpoint/resume). This
module gives the standalone store the same property: every mutation
appends one JSONL record to a write-ahead log, the log compacts into a
full snapshot every N records, and a fresh ``Store(state_dir=...)``
rebuilds objects + resource-version counter from snapshot+WAL before
serving its first read. Controllers then reconcile from the loaded
state exactly as reference controllers do from informer resync.

Format: ``snapshot.json`` = {"rv": N, "objects": [{"kind", "data"}]},
``wal.jsonl`` = {"op": "put"|"delete", "kind", "data"|("ns","name")}
per line. Object payloads are the full serde dict (meta+spec+status),
decoded through the same KIND_REGISTRY the manifest codec uses.
Appends flush to the OS on every record; fsync durability is not
attempted (matching the in-memory store's crash model: a torn final
line is skipped on load).
"""

from __future__ import annotations

import json
import os
from typing import Any

from grove_tpu.api.serde import from_dict, to_dict


def _registry() -> dict[str, type]:
    from grove_tpu.manifest import KIND_REGISTRY
    return KIND_REGISTRY


class StatePersister:
    def __init__(self, state_dir: str, compact_every: int = 1000):
        self.state_dir = state_dir
        self.compact_every = compact_every
        os.makedirs(state_dir, exist_ok=True)
        self.snapshot_path = os.path.join(state_dir, "snapshot.json")
        self.wal_path = os.path.join(state_dir, "wal.jsonl")
        self._wal_file = None
        self._wal_records = 0

    # ---- load ------------------------------------------------------------

    def load(self) -> tuple[list[Any], int]:
        """Return (objects, max_rv) from snapshot + WAL replay."""
        registry = _registry()
        objects: dict[tuple[str, str, str], Any] = {}
        max_rv = 0

        def put(kind: str, data: dict) -> None:
            nonlocal max_rv
            cls = registry.get(kind)
            if cls is None:  # kind from a newer build; preserve nothing
                return
            obj = from_dict(cls, data)
            objects[(kind, obj.meta.namespace, obj.meta.name)] = obj
            max_rv = max(max_rv, obj.meta.resource_version)

        if os.path.exists(self.snapshot_path):
            with open(self.snapshot_path) as f:
                snap = json.load(f)
            max_rv = snap.get("rv", 0)
            for entry in snap.get("objects", []):
                put(entry["kind"], entry["data"])
        if os.path.exists(self.wal_path):
            with open(self.wal_path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        break  # torn tail record: ignore it and stop
                    if rec["op"] == "put":
                        put(rec["kind"], rec["data"])
                    elif rec["op"] == "delete":
                        objects.pop((rec["kind"], rec["ns"], rec["name"]),
                                    None)
                    self._wal_records += 1
        return list(objects.values()), max_rv

    # ---- append ----------------------------------------------------------

    def _append(self, record: dict) -> None:
        if self._wal_file is None:
            self._wal_file = open(self.wal_path, "a")
        self._wal_file.write(json.dumps(record) + "\n")
        self._wal_file.flush()
        self._wal_records += 1

    def record_put(self, obj: Any) -> None:
        self._append({"op": "put", "kind": obj.KIND, "data": to_dict(obj)})

    def record_delete(self, obj: Any) -> None:
        self._append({"op": "delete", "kind": obj.KIND,
                      "ns": obj.meta.namespace, "name": obj.meta.name})

    def maybe_compact(self, objects: list[Any], rv: int) -> bool:
        """Snapshot + truncate the WAL once it exceeds the threshold.
        Caller passes a consistent view (holds the store lock)."""
        if self._wal_records < self.compact_every:
            return False
        self.compact(objects, rv)
        return True

    def compact(self, objects: list[Any], rv: int) -> None:
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"rv": rv,
                       "objects": [{"kind": o.KIND, "data": to_dict(o)}
                                   for o in objects]}, f)
        os.replace(tmp, self.snapshot_path)
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = None
        open(self.wal_path, "w").close()
        self._wal_records = 0

    def close(self) -> None:
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = None
