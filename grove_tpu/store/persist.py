"""Store persistence: WAL + snapshot — the etcd-durability analog.

The reference keeps every scrap of control-plane state in etcd, which is
why operator restart is free (SURVEY.md §5 checkpoint/resume). This
module gives the standalone store the same property: every mutation
appends one JSONL record to a write-ahead log, the log compacts into a
full snapshot every N records, and a fresh ``Store(state_dir=...)``
rebuilds objects + resource-version counter from snapshot+WAL before
serving its first read. Controllers then reconcile from the loaded
state exactly as reference controllers do from informer resync.

Format: ``snapshot.json`` = {"version": V, "rv": N,
"objects": [{"kind", "data"}]}, ``wal.jsonl`` =
{"op": "put"|"delete", "kind", "data"|("ns","name")} per line. Object
payloads are the full serde dict (meta+spec+status), decoded through
the same KIND_REGISTRY the manifest codec uses. Appends flush to the OS
on every record; fsync durability is not attempted (matching the
in-memory store's crash model: a torn final line is skipped on load).

Schema evolution (the reference's self-managed CRD upgrade story,
proposal 436-crd-upgrader): field ADDITIONS are free — serde's
from_dict defaults missing fields and ignores unknown ones — but
renames/restructures need a migration. ``STATE_VERSION`` stamps the
snapshot; ``MIGRATIONS[v]`` rewrites one (kind, data) pair from version
v to v+1 (returning None drops the object). A load of older state runs
the chain and immediately compacts, so the on-disk state is atomically
at the current version before the first new WAL append — a mixed-
version WAL can never exist. State from a NEWER build refuses to load
(downgrades silently corrupting state is the one unrecoverable
failure).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Optional

from grove_tpu.api.serde import from_dict, to_dict

# Current on-disk schema version. Bump when a persisted field is
# renamed/restructured, and register the rewrite in MIGRATIONS.
STATE_VERSION = 2

# version v -> fn(kind, data) -> (kind, data) | None (drop object).
# v1 (round-2 pre-versioning snapshots, no "version" key) is
# structurally identical to v2 — the migration is the identity; its
# purpose is pinning the machinery with a real entry. NOTE: because
# v1 ≡ v2, a headerless WAL (which could be either) replays correctly
# through the v1 chain; any future migration starts at 2, where every
# WAL carries a version header.
MIGRATIONS: dict[int, Callable[[str, dict], Optional[tuple[str, dict]]]] = {
    1: lambda kind, data: (kind, data),
}

# version v -> fn(kind, ns, name) -> (kind, ns, name). Delete records
# carry only the object KEY; a migration that renames a kind (or
# re-namespaces objects) must register the key rewrite here or replayed
# deletes would miss the migrated puts and resurrect deleted objects.
KEY_MIGRATIONS: dict[
    int, Callable[[str, str, str], tuple[str, str, str]]] = {}


class StateVersionError(RuntimeError):
    """State on disk was written by a newer build; refuse to load."""


class StateLockError(RuntimeError):
    """Another process holds the state-dir's single-writer lock."""


# state dirs (realpath) this PROCESS already holds the flock for. The
# lock is cross-PROCESS single-writer protection; within one process,
# sequential Store instances over one dir (the test harness's simulated
# restarts) share the held lock. Entries live until process exit — the
# kernel then releases the flock, even on SIGKILL, which covers every
# DEAD holder without a heartbeat protocol. The lease below covers the
# one case flock can't: a holder that is alive but WEDGED.
_PROCESS_LOCKS: dict[str, int] = {}

# Lease TTL for wedged-holder fencing (reference leader election renews
# a Lease with a TTL, manager.go:55-147 — a leader that stops renewing
# loses leadership even if its process is still alive). The holder
# re-stamps <state_dir>/LEASE every TTL/5; a takeover standby that sees
# the flock held AND the lease stale beyond the TTL SIGKILLs the holder
# (fencing — a flock cannot be revoked from outside, so terminating the
# wedged process is what releases it). Must be consistent across the
# processes sharing a state dir.
def _lease_ttl() -> float:
    return float(os.environ.get("GROVE_LEASE_TTL", 10.0))


def _lease_path(state_dir: str) -> str:
    return os.path.join(state_dir, "LEASE")


def _stamp_lease(state_dir: str) -> None:
    import time
    path = _lease_path(state_dir)
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            f.write(json.dumps({"pid": os.getpid(), "ts": time.time()}))
        os.replace(tmp, path)                 # atomic: readers never tear
    except OSError:
        pass                                  # lease is advisory liveness


def _start_lease_heartbeat(state_dir: str) -> None:
    """Daemon renewal thread for the process lifetime. A SIGSTOPped or
    otherwise wedged process stops renewing (all its threads freeze),
    which is exactly the signal the standby fences on."""
    import threading
    import time

    _stamp_lease(state_dir)

    def loop() -> None:
        interval = max(_lease_ttl() / 5.0, 0.05)
        while True:
            time.sleep(interval)
            _stamp_lease(state_dir)

    threading.Thread(target=loop, name="state-lease", daemon=True).start()


def _maybe_fence_wedged_holder(state_dir: str, lock_fd: int) -> None:
    """SIGKILL the lock holder iff its lease expired AND the lease pid
    still matches the LOCK stamp (guards against recycled pids and the
    window where a new holder just took over)."""
    import signal
    import time
    try:
        with open(_lease_path(state_dir)) as f:
            lease = json.loads(f.read())
        pid, ts = int(lease["pid"]), float(lease["ts"])
    except (OSError, ValueError, KeyError, TypeError):
        return          # no lease evidence: wait for flock release only
    if time.time() - ts <= _lease_ttl():
        return
    try:
        os.lseek(lock_fd, 0, os.SEEK_SET)
        holder = os.read(lock_fd, 256).decode(errors="replace")
        holder_pid = int(holder.strip().split("pid=")[1].split()[0])
    except (OSError, IndexError, ValueError):
        return
    # Exact pid comparison — a substring match would let a stale lease
    # whose pid is a numeric prefix of the holder's (123 vs 1234) fence
    # an unrelated (possibly recycled) pid.
    if holder_pid != pid or pid <= 1 or pid == os.getpid():
        return
    # TOCTOU guard: between the stamp check above and the signal, the
    # holder can exit and the OS can recycle the pid onto an unrelated
    # process. pidfd_open pins THIS incarnation of the pid; the flock
    # probe afterwards proves the pinned process is still the holder
    # (a holder that exited releases the flock — then there is nothing
    # to kill), and the stamp re-read catches a new holder that
    # acquired in between. Only then is the signal sent — to the
    # pidfd, which cannot retarget a recycled pid.
    import fcntl
    pidfd = -1
    if hasattr(os, "pidfd_open"):
        try:
            pidfd = os.pidfd_open(pid)
        except ProcessLookupError:
            return                            # gone already: flock will free
        except OSError:
            pidfd = -1    # fd pressure/EPERM etc.: the fence must still
            #               happen — fall back to the narrowed os.kill
    try:
        try:
            fcntl.flock(lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            pass                              # still held: proceed to verify
        else:
            return      # holder exited; caller's loop now owns the lock
        try:
            os.lseek(lock_fd, 0, os.SEEK_SET)
            holder2 = os.read(lock_fd, 256).decode(errors="replace")
            pid2 = int(holder2.strip().split("pid=")[1].split()[0])
        except (OSError, IndexError, ValueError):
            return
        if pid2 != pid:
            return                            # a new holder took over
        try:
            if pidfd >= 0:
                signal.pidfd_send_signal(pidfd, signal.SIGKILL)
            else:       # non-pidfd platforms keep the narrowed os.kill
                os.kill(pid, signal.SIGKILL)  # works on stopped processes
        except (ProcessLookupError, PermissionError, OSError):
            pass                              # gone already / not ours
    finally:
        if pidfd >= 0:
            os.close(pidfd)


def _acquire_state_lock(state_dir: str, wait: bool) -> None:
    """Exclusive flock on <state_dir>/LOCK — the leader-election analog
    (reference runs leader-elected, manager.go:55-147; without this, two
    ``serve --state-dir X`` processes interleave WAL appends and clobber
    each other's snapshots, silently corrupting the state the WAL exists
    to protect). ``wait=True`` waits until the current holder exits OR
    its lease goes stale — a holder that is alive but wedged (hung
    relay, deadlock, SIGSTOP) is fenced by SIGKILL after the lease TTL,
    closing the liveness hole a pure flock leaves open. ``wait=False``
    refuses immediately with the holder's identity."""
    import fcntl
    import time

    key = os.path.realpath(state_dir)
    if key in _PROCESS_LOCKS:
        return
    fd = os.open(os.path.join(state_dir, "LOCK"),
                 os.O_CREAT | os.O_RDWR, 0o644)
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            break
        except OSError:
            if not wait:
                holder = ""
                try:
                    holder = os.read(fd, 256).decode(
                        errors="replace").strip()
                except OSError:
                    pass
                os.close(fd)
                raise StateLockError(
                    f"state dir {state_dir!r} is locked by another process"
                    + (f" ({holder})" if holder else "") +
                    "; a second writer would interleave WAL appends and "
                    "corrupt control-plane state. Stop the other serve, or "
                    "run with takeover enabled (grovectl serve --takeover) "
                    "to wait for its lease") from None
            _maybe_fence_wedged_holder(state_dir, fd)
            time.sleep(min(_lease_ttl() / 10.0, 0.2))
    # Held. Stamp the holder for the refusal diagnostic above, then keep
    # the lease fresh for the process lifetime. (Rewind first: the
    # fencing path may have read this fd, and ftruncate does not reset
    # the offset — writing at a nonzero offset would leave NUL bytes
    # before the stamp.)
    os.ftruncate(fd, 0)
    os.lseek(fd, 0, os.SEEK_SET)
    os.write(fd, f"pid={os.getpid()}\n".encode())
    _PROCESS_LOCKS[key] = fd
    _start_lease_heartbeat(state_dir)


def migrate_object(kind: str, data: dict,
                   from_version: int) -> Optional[tuple[str, dict]]:
    """Run the migration chain from ``from_version`` to STATE_VERSION."""
    for v in range(from_version, STATE_VERSION):
        step = MIGRATIONS.get(v)
        if step is None:
            raise StateVersionError(
                f"no migration registered for state version {v} -> {v + 1}")
        migrated = step(kind, data)
        if migrated is None:
            return None
        kind, data = migrated
    return kind, data


def migrate_key(kind: str, ns: str, name: str,
                from_version: int) -> tuple[str, str, str]:
    """Run the key-migration chain (identity unless registered)."""
    for v in range(from_version, STATE_VERSION):
        step = KEY_MIGRATIONS.get(v)
        if step is not None:
            kind, ns, name = step(kind, ns, name)
    return kind, ns, name


def _registry() -> dict[str, type]:
    from grove_tpu.manifest import KIND_REGISTRY
    return KIND_REGISTRY


class StatePersister:
    def __init__(self, state_dir: str, compact_every: int = 1000,
                 takeover_wait: bool = False):
        self.state_dir = state_dir
        self.compact_every = compact_every
        os.makedirs(state_dir, exist_ok=True)
        # Single-writer guard BEFORE the first read: a takeover must
        # re-load state after the previous holder's final appends.
        _acquire_state_lock(state_dir, wait=takeover_wait)
        self.snapshot_path = os.path.join(state_dir, "snapshot.json")
        self.wal_path = os.path.join(state_dir, "wal.jsonl")
        self._wal_file = None
        self._wal_records = 0

    # ---- load ------------------------------------------------------------

    def load(self) -> tuple[list[Any], int]:
        """Return (objects, max_rv) from snapshot + WAL replay, running
        schema migrations when the state predates STATE_VERSION (and
        compacting immediately after, so disk is atomically current)."""
        registry = _registry()
        objects: dict[tuple[str, str, str], Any] = {}
        max_rv = 0
        snap_version = STATE_VERSION
        # WAL records are versioned by the WAL'S OWN header, never by
        # the snapshot: a crash between the upgrade-compact's snapshot
        # replace and its WAL truncation leaves a current-version
        # snapshot next to an old WAL — inferring the WAL's version
        # from the snapshot would replay those records unmigrated.
        # A headerless non-empty WAL is by construction pre-versioning.
        wal_version = 1

        def put(kind: str, data: dict, version: int) -> None:
            nonlocal max_rv
            if version < STATE_VERSION:
                migrated = migrate_object(kind, data, version)
                if migrated is None:
                    return
                kind, data = migrated
            cls = registry.get(kind)
            if cls is None:  # kind from a newer build; preserve nothing
                return
            obj = from_dict(cls, data)
            objects[(kind, obj.meta.namespace, obj.meta.name)] = obj
            max_rv = max(max_rv, obj.meta.resource_version)

        if os.path.exists(self.snapshot_path):
            with open(self.snapshot_path) as f:
                snap = json.load(f)
            snap_version = snap.get("version", 1)
            if snap_version > STATE_VERSION:
                raise StateVersionError(
                    f"state dir {self.state_dir!r} is at schema version "
                    f"{snap_version}, written by a newer build than this "
                    f"one (STATE_VERSION={STATE_VERSION}); refusing to "
                    "load — downgrading would silently corrupt "
                    "control-plane state")
            max_rv = snap.get("rv", 0)
            for entry in snap.get("objects", []):
                put(entry["kind"], entry["data"], snap_version)
        if os.path.exists(self.wal_path):
            with open(self.wal_path, "rb") as f:
                raw = f.read()
            good = 0   # byte length of the valid prefix
            for line in raw.split(b"\n"):
                if not line.strip():
                    good += len(line) + 1
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    break  # torn tail record: stop (and truncate below)
                good += len(line) + 1
                if rec["op"] == "version":
                    wal_version = rec["v"]
                    if wal_version > STATE_VERSION:
                        raise StateVersionError(
                            f"state dir {self.state_dir!r} WAL is at "
                            f"schema version {wal_version}, written by a "
                            f"newer build (STATE_VERSION="
                            f"{STATE_VERSION}); refusing to load")
                    continue
                if rec["op"] == "put":
                    put(rec["kind"], rec["data"], wal_version)
                elif rec["op"] == "delete":
                    objects.pop(migrate_key(rec["kind"], rec["ns"],
                                            rec["name"], wal_version),
                                None)
                self._wal_records += 1
            good = min(good, len(raw))
            if good < len(raw):
                # Truncate the torn tail NOW: appending after it would
                # merge two records into one undecodable line, and the
                # NEXT restart would then discard every record after
                # the tear.
                with open(self.wal_path, "r+b") as f:
                    f.truncate(good)
            elif raw and not raw.endswith(b"\n"):
                # Final record's JSON is complete but its newline was
                # lost (torn exactly at the line boundary): terminate it
                # before any append, or the next record concatenates onto
                # it and the merged line loses BOTH records on the
                # following load.
                with open(self.wal_path, "ab") as f:
                    f.write(b"\n")
        loaded = list(objects.values())
        if snap_version < STATE_VERSION or (
                self._wal_records and wal_version < STATE_VERSION):
            # Upgrade completes atomically BEFORE the first new append —
            # a WAL can then never mix schema versions.
            self.compact(loaded, max_rv)
        return loaded, max_rv

    # ---- append ----------------------------------------------------------

    def _append(self, record: dict) -> None:
        if self._wal_file is None:
            fresh = (not os.path.exists(self.wal_path)
                     or os.path.getsize(self.wal_path) == 0)
            self._wal_file = open(self.wal_path, "a")
            if fresh:
                # Fresh WAL leads with its schema version: a WAL-only
                # state dir (no snapshot yet) must still refuse to load
                # in an older build.
                self._wal_file.write(json.dumps(
                    {"op": "version", "v": STATE_VERSION}) + "\n")
        self._wal_file.write(json.dumps(record) + "\n")
        self._wal_file.flush()
        self._wal_records += 1

    def record_put(self, obj: Any) -> None:
        self._append({"op": "put", "kind": obj.KIND, "data": to_dict(obj)})

    def record_delete(self, obj: Any) -> None:
        self._append({"op": "delete", "kind": obj.KIND,
                      "ns": obj.meta.namespace, "name": obj.meta.name})

    def maybe_compact(self, objects: list[Any], rv: int) -> bool:
        """Snapshot + truncate the WAL once it exceeds the threshold.
        Caller passes a consistent view (holds the store lock)."""
        if self._wal_records < self.compact_every:
            return False
        self.compact(objects, rv)
        return True

    def compact(self, objects: list[Any], rv: int) -> None:
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": STATE_VERSION, "rv": rv,
                       "objects": [{"kind": o.KIND, "data": to_dict(o)}
                                   for o in objects]}, f)
        os.replace(tmp, self.snapshot_path)
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = None
        open(self.wal_path, "w").close()
        self._wal_records = 0

    def close(self) -> None:
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = None
