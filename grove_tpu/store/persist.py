"""Store persistence: WAL + snapshot — the etcd-durability analog.

The reference keeps every scrap of control-plane state in etcd, which is
why operator restart is free (SURVEY.md §5 checkpoint/resume). This
module gives the standalone store the same property: every mutation
appends one JSONL record to a write-ahead log, the log compacts into a
full snapshot every N records, and a fresh ``Store(state_dir=...)``
rebuilds objects + resource-version counter from snapshot+WAL before
serving its first read. Controllers then reconcile from the loaded
state exactly as reference controllers do from informer resync.

Format: ``snapshot.json`` = {"version": V, "rv": N, "epoch": E,
"objects": [{"kind", "data"}]}, ``wal.jsonl`` =
{"op": "put"|"delete"|"epoch"|"rotated", "kind", "data"|("ns","name"),
"rv", "e"} per line. Object payloads are the full serde dict
(meta+spec+status), decoded through the same KIND_REGISTRY the manifest
codec uses. Appends flush to the OS on every record; fsync durability
is not attempted for object records (matching the in-memory store's
crash model: a torn final line is skipped on load) — only the fencing
``epoch`` record is fsynced, because the epoch bump IS the fence a new
leader relies on.

Leadership fencing (grove_tpu/ha, proposal 0002): the store's monotonic
fencing epoch is persisted three ways — in the snapshot header, as
``{"op": "epoch"}`` WAL records, and mirrored into a tiny ``EPOCH``
sidecar (atomic tmp+rename+fsync) so the warm-start loader can learn it
without decoding the snapshot. Every put/delete record is stamped with
the epoch in effect (``"e"``); replay drops records whose stamp
predates the highest epoch seen so far — a zombie leader that appends
to the WAL after a takeover bumped the epoch loses those records on
the next load instead of silently corrupting state.

Compaction runs IN OPERATION without stalling writers: when the WAL
crosses the threshold the live file is rotated (footer record + fsync +
rename to ``wal.compacting.jsonl``) under the store lock — cheap — and
a background thread writes the snapshot (tmp + fsync + rename + dir
fsync) before unlinking the rotated segment. Load replays
snapshot → segment → live WAL; a segment whose footer rv the snapshot
already covers is skipped (the crash-between-replace-and-unlink case).

Schema evolution (the reference's self-managed CRD upgrade story,
proposal 436-crd-upgrader): field ADDITIONS are free — serde's
from_dict defaults missing fields and ignores unknown ones — but
renames/restructures need a migration. ``STATE_VERSION`` stamps the
snapshot; ``MIGRATIONS[v]`` rewrites one (kind, data) pair from version
v to v+1 (returning None drops the object). A load of older state runs
the chain and immediately compacts, so the on-disk state is atomically
at the current version before the first new WAL append — a mixed-
version WAL can never exist. State from a NEWER build refuses to load
(downgrades silently corrupting state is the one unrecoverable
failure).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Callable, Optional

from grove_tpu.api.serde import from_dict, to_dict

# The snapshot header's rv/epoch, readable from the file head:
# _write_snapshot emits {"version": V, "rv": N, "epoch": E, ...} with
# these keys first, so the warm loader learns the compaction horizon
# and the base fencing term without parsing the whole file.
_SNAP_RV_RE = re.compile(r'"rv":\s*(\d+)')
_SNAP_EPOCH_RE = re.compile(r'"epoch":\s*(\d+)')
# Epoch records as raw WAL lines (we write them with exactly this key
# order), so the warm loader can find the last bump BEFORE its cut
# point with a string-prefix scan instead of decoding every payload.
_EPOCH_LINE_PREFIX = b'{"op": "epoch"'

# Current on-disk schema version. Bump when a persisted field is
# renamed/restructured, and register the rewrite in MIGRATIONS.
STATE_VERSION = 2

# version v -> fn(kind, data) -> (kind, data) | None (drop object).
# v1 (round-2 pre-versioning snapshots, no "version" key) is
# structurally identical to v2 — the migration is the identity; its
# purpose is pinning the machinery with a real entry. NOTE: because
# v1 ≡ v2, a headerless WAL (which could be either) replays correctly
# through the v1 chain; any future migration starts at 2, where every
# WAL carries a version header.
MIGRATIONS: dict[int, Callable[[str, dict], Optional[tuple[str, dict]]]] = {
    1: lambda kind, data: (kind, data),
}

# version v -> fn(kind, ns, name) -> (kind, ns, name). Delete records
# carry only the object KEY; a migration that renames a kind (or
# re-namespaces objects) must register the key rewrite here or replayed
# deletes would miss the migrated puts and resurrect deleted objects.
KEY_MIGRATIONS: dict[
    int, Callable[[str, str, str], tuple[str, str, str]]] = {}


class StateVersionError(RuntimeError):
    """State on disk was written by a newer build; refuse to load."""


class StateLockError(RuntimeError):
    """Another process holds the state-dir's single-writer lock."""


# state dirs (realpath) this PROCESS already holds the flock for. The
# lock is cross-PROCESS single-writer protection; within one process,
# sequential Store instances over one dir (the test harness's simulated
# restarts) share the held lock. Entries live until process exit — the
# kernel then releases the flock, even on SIGKILL, which covers every
# DEAD holder without a heartbeat protocol. The lease below covers the
# one case flock can't: a holder that is alive but WEDGED.
_PROCESS_LOCKS: dict[str, int] = {}

# Lease TTL for wedged-holder fencing (reference leader election renews
# a Lease with a TTL, manager.go:55-147 — a leader that stops renewing
# loses leadership even if its process is still alive). The holder
# re-stamps <state_dir>/LEASE every TTL/5; a takeover standby that sees
# the flock held AND the lease stale beyond the TTL SIGKILLs the holder
# (fencing — a flock cannot be revoked from outside, so terminating the
# wedged process is what releases it). Must be consistent across the
# processes sharing a state dir.
def _lease_ttl() -> float:
    return float(os.environ.get("GROVE_LEASE_TTL", 10.0))


def _lease_path(state_dir: str) -> str:
    return os.path.join(state_dir, "LEASE")


def _stamp_lease(state_dir: str) -> None:
    import time
    path = _lease_path(state_dir)
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            f.write(json.dumps({"pid": os.getpid(), "ts": time.time()}))
        os.replace(tmp, path)                 # atomic: readers never tear
    except OSError:
        pass                                  # lease is advisory liveness


# Heartbeat stop events per state dir (realpath): release_state_lock
# must silence the renewal thread, or a released dir keeps getting
# stamped by a non-holder forever (confusing the next takeover's
# staleness check).
_HEARTBEAT_STOPS: dict[str, Any] = {}


def _start_lease_heartbeat(state_dir: str) -> None:
    """Daemon renewal thread for the lock-hold lifetime. A SIGSTOPped or
    otherwise wedged process stops renewing (all its threads freeze),
    which is exactly the signal the standby fences on."""
    import threading

    _stamp_lease(state_dir)
    stop = threading.Event()
    _HEARTBEAT_STOPS[os.path.realpath(state_dir)] = stop

    def loop() -> None:
        interval = max(_lease_ttl() / 5.0, 0.05)
        while not stop.wait(interval):
            _stamp_lease(state_dir)

    threading.Thread(target=loop, name="state-lease", daemon=True).start()


def release_state_lock(state_dir: str) -> bool:
    """Voluntarily release this process's hold on a state dir: stop the
    lease heartbeat and close the flock'd fd (the kernel releases the
    flock on close). The in-process leadership-handoff primitive —
    normal leaders hold until process exit (the kernel releases even on
    SIGKILL), but tests and the demote path need an explicit release so
    a takeover in the SAME process exercises the genuine acquisition
    path. Returns False when this process held no lock on the dir."""
    key = os.path.realpath(state_dir)
    fd = _PROCESS_LOCKS.pop(key, None)
    stop = _HEARTBEAT_STOPS.pop(key, None)
    if stop is not None:
        stop.set()
    if fd is None:
        return False
    try:
        os.close(fd)
    except OSError:
        pass
    return True


def _maybe_fence_wedged_holder(state_dir: str, lock_fd: int) -> None:
    """SIGKILL the lock holder iff its lease expired AND the lease pid
    still matches the LOCK stamp (guards against recycled pids and the
    window where a new holder just took over)."""
    import signal
    import time
    try:
        with open(_lease_path(state_dir)) as f:
            lease = json.loads(f.read())
        pid, ts = int(lease["pid"]), float(lease["ts"])
    except (OSError, ValueError, KeyError, TypeError):
        return          # no lease evidence: wait for flock release only
    if time.time() - ts <= _lease_ttl():
        return
    try:
        os.lseek(lock_fd, 0, os.SEEK_SET)
        holder = os.read(lock_fd, 256).decode(errors="replace")
        holder_pid = int(holder.strip().split("pid=")[1].split()[0])
    except (OSError, IndexError, ValueError):
        return
    # Exact pid comparison — a substring match would let a stale lease
    # whose pid is a numeric prefix of the holder's (123 vs 1234) fence
    # an unrelated (possibly recycled) pid.
    if holder_pid != pid or pid <= 1 or pid == os.getpid():
        return
    # TOCTOU guard: between the stamp check above and the signal, the
    # holder can exit and the OS can recycle the pid onto an unrelated
    # process. pidfd_open pins THIS incarnation of the pid; the flock
    # probe afterwards proves the pinned process is still the holder
    # (a holder that exited releases the flock — then there is nothing
    # to kill), and the stamp re-read catches a new holder that
    # acquired in between. Only then is the signal sent — to the
    # pidfd, which cannot retarget a recycled pid.
    import fcntl
    pidfd = -1
    if hasattr(os, "pidfd_open"):
        try:
            pidfd = os.pidfd_open(pid)
        except ProcessLookupError:
            return                            # gone already: flock will free
        except OSError:
            pidfd = -1    # fd pressure/EPERM etc.: the fence must still
            #               happen — fall back to the narrowed os.kill
    try:
        try:
            fcntl.flock(lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            pass                              # still held: proceed to verify
        else:
            return      # holder exited; caller's loop now owns the lock
        try:
            os.lseek(lock_fd, 0, os.SEEK_SET)
            holder2 = os.read(lock_fd, 256).decode(errors="replace")
            pid2 = int(holder2.strip().split("pid=")[1].split()[0])
        except (OSError, IndexError, ValueError):
            return
        if pid2 != pid:
            return                            # a new holder took over
        try:
            if pidfd >= 0:
                signal.pidfd_send_signal(pidfd, signal.SIGKILL)
            else:       # non-pidfd platforms keep the narrowed os.kill
                os.kill(pid, signal.SIGKILL)  # works on stopped processes
        except (ProcessLookupError, PermissionError, OSError):
            pass                              # gone already / not ours
    finally:
        if pidfd >= 0:
            os.close(pidfd)


def _acquire_state_lock(state_dir: str, wait: bool) -> None:
    """Exclusive flock on <state_dir>/LOCK — the leader-election analog
    (reference runs leader-elected, manager.go:55-147; without this, two
    ``serve --state-dir X`` processes interleave WAL appends and clobber
    each other's snapshots, silently corrupting the state the WAL exists
    to protect). ``wait=True`` waits until the current holder exits OR
    its lease goes stale — a holder that is alive but wedged (hung
    relay, deadlock, SIGSTOP) is fenced by SIGKILL after the lease TTL,
    closing the liveness hole a pure flock leaves open. ``wait=False``
    refuses immediately with the holder's identity."""
    import fcntl
    import time

    key = os.path.realpath(state_dir)
    if key in _PROCESS_LOCKS:
        return
    fd = os.open(os.path.join(state_dir, "LOCK"),
                 os.O_CREAT | os.O_RDWR, 0o644)
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            break
        except OSError:
            if not wait:
                holder = ""
                try:
                    holder = os.read(fd, 256).decode(
                        errors="replace").strip()
                except OSError:
                    pass
                os.close(fd)
                raise StateLockError(
                    f"state dir {state_dir!r} is locked by another process"
                    + (f" ({holder})" if holder else "") +
                    "; a second writer would interleave WAL appends and "
                    "corrupt control-plane state. Stop the other serve, or "
                    "run with takeover enabled (grovectl serve --takeover) "
                    "to wait for its lease") from None
            _maybe_fence_wedged_holder(state_dir, fd)
            time.sleep(min(_lease_ttl() / 10.0, 0.2))
    # Held. Stamp the holder for the refusal diagnostic above, then keep
    # the lease fresh for the process lifetime. (Rewind first: the
    # fencing path may have read this fd, and ftruncate does not reset
    # the offset — writing at a nonzero offset would leave NUL bytes
    # before the stamp.)
    os.ftruncate(fd, 0)
    os.lseek(fd, 0, os.SEEK_SET)
    os.write(fd, f"pid={os.getpid()}\n".encode())
    _PROCESS_LOCKS[key] = fd
    _start_lease_heartbeat(state_dir)


def migrate_object(kind: str, data: dict,
                   from_version: int) -> Optional[tuple[str, dict]]:
    """Run the migration chain from ``from_version`` to STATE_VERSION."""
    for v in range(from_version, STATE_VERSION):
        step = MIGRATIONS.get(v)
        if step is None:
            raise StateVersionError(
                f"no migration registered for state version {v} -> {v + 1}")
        migrated = step(kind, data)
        if migrated is None:
            return None
        kind, data = migrated
    return kind, data


def migrate_key(kind: str, ns: str, name: str,
                from_version: int) -> tuple[str, str, str]:
    """Run the key-migration chain (identity unless registered)."""
    for v in range(from_version, STATE_VERSION):
        step = KEY_MIGRATIONS.get(v)
        if step is not None:
            kind, ns, name = step(kind, ns, name)
    return kind, ns, name


def _registry() -> dict[str, type]:
    from grove_tpu.manifest import KIND_REGISTRY
    return KIND_REGISTRY


class StatePersister:
    def __init__(self, state_dir: str, compact_every: int = 1000,
                 takeover_wait: bool = False, compact_async: bool = True):
        self.state_dir = state_dir
        self.compact_every = compact_every
        self.compact_async = compact_async
        os.makedirs(state_dir, exist_ok=True)
        # Single-writer guard BEFORE the first read: a takeover must
        # re-load state after the previous holder's final appends.
        _acquire_state_lock(state_dir, wait=takeover_wait)
        self.snapshot_path = os.path.join(state_dir, "snapshot.json")
        self.wal_path = os.path.join(state_dir, "wal.jsonl")
        # Rotated-but-not-yet-folded WAL segment (background
        # compaction in flight, or a crash mid-compaction).
        self.segment_path = os.path.join(state_dir, "wal.compacting.jsonl")
        self.epoch_path = os.path.join(state_dir, "EPOCH")
        self._wal_file = None
        self._wal_records = 0
        self._compact_thread = None
        # How the last load ran — the warm-start bench asserts the tail
        # path actually skipped work ({"mode": "warm"|"full",
        # "decoded": n, "lines": m}).
        self.last_load: dict[str, Any] = {}

    # ---- load ------------------------------------------------------------

    def _read_records(self, path: str, repair: bool = False) -> list[dict]:
        """Decode one JSONL WAL file into records, stopping at a torn
        tail. ``repair`` truncates the tear / restores a lost final
        newline in place (only safe on the LIVE wal — the rotated
        segment is immutable history)."""
        with open(path, "rb") as f:
            raw = f.read()
        records: list[dict] = []
        good = 0   # byte length of the valid prefix
        for line in raw.split(b"\n"):
            if not line.strip():
                good += len(line) + 1
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                break  # torn tail record: stop (and truncate below)
            good += len(line) + 1
        good = min(good, len(raw))
        if repair:
            if good < len(raw):
                # Truncate the torn tail NOW: appending after it would
                # merge two records into one undecodable line, and the
                # NEXT restart would then discard every record after
                # the tear.
                with open(path, "r+b") as f:
                    f.truncate(good)
            elif raw and not raw.endswith(b"\n"):
                # Final record's JSON is complete but its newline was
                # lost (torn exactly at the line boundary): terminate it
                # before any append, or the next record concatenates
                # onto it and the merged line loses BOTH records on the
                # following load.
                with open(path, "ab") as f:
                    f.write(b"\n")
        return records

    def _sidecar_epoch(self) -> int:
        try:
            with open(self.epoch_path) as f:
                return int(json.load(f)["epoch"])
        except (OSError, ValueError, KeyError, TypeError):
            return 0

    def load(self) -> tuple[list[Any], int, int]:
        """Return (objects, max_rv, epoch) from snapshot + rotated
        segment + WAL replay, running schema migrations when the state
        predates STATE_VERSION (and compacting immediately after, so
        disk is atomically current). Records stamped with an epoch
        older than the highest epoch seen so far are dropped — they are
        a fenced zombie leader's post-takeover appends."""
        registry = _registry()
        objects: dict[tuple[str, str, str], Any] = {}
        max_rv = 0
        epoch = 0
        snap_version = STATE_VERSION
        snap_rv = 0
        snap_objects = 0

        def put(kind: str, data: dict, version: int) -> None:
            nonlocal max_rv
            if version < STATE_VERSION:
                migrated = migrate_object(kind, data, version)
                if migrated is None:
                    return
                kind, data = migrated
            cls = registry.get(kind)
            if cls is None:  # kind from a newer build; preserve nothing
                return
            obj = from_dict(cls, data)
            objects[(kind, obj.meta.namespace, obj.meta.name)] = obj
            max_rv = max(max_rv, obj.meta.resource_version)

        if os.path.exists(self.snapshot_path):
            with open(self.snapshot_path) as f:
                snap = json.load(f)
            snap_version = snap.get("version", 1)
            if snap_version > STATE_VERSION:
                raise StateVersionError(
                    f"state dir {self.state_dir!r} is at schema version "
                    f"{snap_version}, written by a newer build than this "
                    f"one (STATE_VERSION={STATE_VERSION}); refusing to "
                    "load — downgrading would silently corrupt "
                    "control-plane state")
            max_rv = snap_rv = snap.get("rv", 0)
            epoch = snap.get("epoch", 0)
            for entry in snap.get("objects", []):
                put(entry["kind"], entry["data"], snap_version)
            snap_objects = len(snap.get("objects", []))

        total_lines = 0
        had_old_wal = False
        segment_pending = False

        def replay(records: list[dict]) -> None:
            """One WAL file's records onto ``objects``. WAL records are
            versioned by the FILE'S OWN header, never by the snapshot: a
            crash between the upgrade-compact's snapshot replace and its
            WAL truncation leaves a current-version snapshot next to an
            old WAL. A headerless non-empty WAL is pre-versioning."""
            nonlocal epoch, had_old_wal, max_rv
            wal_version = 1
            for rec in records:
                op = rec["op"]
                if op == "version":
                    wal_version = rec["v"]
                    if wal_version > STATE_VERSION:
                        raise StateVersionError(
                            f"state dir {self.state_dir!r} WAL is at "
                            f"schema version {wal_version}, written by a "
                            f"newer build (STATE_VERSION="
                            f"{STATE_VERSION}); refusing to load")
                    continue
                if op == "epoch":
                    epoch = max(epoch, int(rec["epoch"]))
                    continue
                if op == "rotated":
                    continue
                # Zombie-leader fence at replay time: a record stamped
                # with an epoch older than one already seen was appended
                # by a deposed writer AFTER the takeover bump — drop it.
                if int(rec.get("e", epoch)) < epoch:
                    continue
                if op == "put":
                    put(rec["kind"], rec["data"], wal_version)
                elif op == "delete":
                    objects.pop(migrate_key(rec["kind"], rec["ns"],
                                            rec["name"], wal_version),
                                None)
                    # Deletes allocate their own seq (stamped since the
                    # HA work): count it into max_rv, or a WAL ending in
                    # deletes reloads into a store that REISSUES those
                    # rvs — and with them, watch seqs.
                    max_rv = max(max_rv, int(rec.get("rv", 0)))
                self._wal_records += 1
            if wal_version < STATE_VERSION and records:
                had_old_wal = True

        if os.path.exists(self.segment_path):
            try:
                seg = self._read_records(self.segment_path)
            except FileNotFoundError:
                seg = []    # a racing background compaction folded it
            footer_rv = next(
                (r["rv"] for r in reversed(seg) if r["op"] == "rotated"),
                None)
            if footer_rv is not None and snap_rv >= footer_rv:
                # Crash between snapshot replace and segment unlink:
                # the snapshot already folds every segment record in.
                try:
                    os.unlink(self.segment_path)
                except OSError:
                    segment_pending = True
            else:
                # Crash between rotation and snapshot replace: the
                # segment is the WAL's older half — replay it first.
                total_lines += len(seg)
                replay(seg)
                segment_pending = True
        if os.path.exists(self.wal_path):
            live = self._read_records(self.wal_path, repair=True)
            total_lines += len(live)
            replay(live)
        epoch = max(epoch, self._sidecar_epoch())
        loaded = list(objects.values())
        self.last_load = {"mode": "full", "decoded": total_lines,
                          "lines": total_lines,
                          "snapshot_objects": snap_objects}
        if snap_version < STATE_VERSION or had_old_wal or segment_pending:
            # Upgrade (and any leftover compaction segment) completes
            # atomically BEFORE the first new append — a WAL can then
            # never mix schema versions, and the segment never outlives
            # one load.
            self.compact(loaded, max_rv, epoch)
        return loaded, max_rv, epoch

    def load_warm(self, warm: dict[tuple[str, str, str], Any],
                  warm_rv: int) -> tuple[list[Any], int, int] | None:
        """Warm-start load (the hot standby's promotion path): the
        caller's mirror already holds the exact store state at
        ``warm_rv`` (maintained from the leader's watch stream), so
        only the WAL delta past it needs decoding — at a 300-pod deploy
        the full WAL is thousands of full-object JSON payloads and the
        delta is near zero. Returns None whenever the tail-only read
        cannot be PROVEN equivalent to a full load (compaction segment
        present, snapshot newer than the mirror, pre-epoch delete
        records, old schema) — the caller falls back to ``load()``.

        Scans the live WAL backwards, decoding lines until one at or
        below ``warm_rv``; puts carry their rv inside the payload,
        deletes carry a top-level ``rv`` stamp (records without one are
        a fallback trigger). Epoch comes from the sidecar plus any
        epoch records in the decoded tail — the sidecar is rewritten on
        every bump precisely so this path never has to scan the whole
        WAL for the current term."""
        registry = _registry()
        if os.path.exists(self.segment_path):
            # A rotated-but-unfolded segment (the leader died between
            # rotation and the snapshot landing — near-certain when a
            # kill races a compaction). Every segment record predates
            # its rotation footer, so a mirror at or past the footer
            # rv COVERS the whole segment: skip it. Anything else
            # falls back to the full load.
            try:
                with open(self.segment_path, "rb") as f:
                    raw_seg = f.read()
                last = raw_seg.rstrip(b"\n").rsplit(b"\n", 1)[-1]
                rec = json.loads(last)
                if rec.get("op") != "rotated" \
                        or int(rec["rv"]) > warm_rv:
                    return None
            except (OSError, ValueError, KeyError, TypeError):
                return None
        snap_rv = 0
        snap_epoch = 0
        if os.path.exists(self.snapshot_path):
            # The snapshot header is written first ({"version", "rv",
            # "epoch", ...) — read only the head, not the whole file.
            with open(self.snapshot_path, "rb") as f:
                head = f.read(256).decode(errors="replace")
            m = _SNAP_RV_RE.search(head)
            if m is None:
                return None
            snap_rv = int(m.group(1))
            m = _SNAP_EPOCH_RE.search(head)
            if m is not None:
                snap_epoch = int(m.group(1))
        if snap_rv > warm_rv:
            # Records in (warm_rv, snap_rv] were compacted out of the
            # WAL; the mirror saw them via watch, but proving that is
            # the contiguity guard's job — be conservative.
            return None
        if not os.path.exists(self.wal_path):
            objects = dict(warm)
            epoch = max(snap_epoch, self._sidecar_epoch())
            self.last_load = {"mode": "warm", "decoded": 0, "lines": 0}
            return list(objects.values()), warm_rv, epoch
        with open(self.wal_path, "rb") as f:
            raw = f.read()
        # Tail repair BEFORE anything else, exactly as load() does via
        # _read_records(repair=True): the promoted store appends to
        # this file, and appending onto a torn final line would merge
        # two records into one undecodable line — the NEXT load would
        # then discard every record after the tear (all the new
        # leader's post-failover writes).
        if raw and not raw.endswith(b"\n"):
            last = raw.rsplit(b"\n", 1)[-1]
            try:
                json.loads(last)
            except ValueError:
                # Torn mid-record: truncate the partial line.
                with open(self.wal_path, "r+b") as f:
                    f.truncate(len(raw) - len(last))
                raw = raw[:len(raw) - len(last)]
            else:
                # Complete JSON, lost newline: re-terminate it.
                with open(self.wal_path, "ab") as f:
                    f.write(b"\n")
        lines = [ln for ln in raw.split(b"\n") if ln.strip()]
        # Schema gate: decode the header line only. ANY version other
        # than ours falls back — older needs migrations, and NEWER must
        # reach load()'s StateVersionError refusal (a warm path that
        # silently decoded a newer build's records would be the exact
        # downgrade corruption the version header exists to prevent).
        if lines:
            try:
                first = json.loads(lines[0])
            except ValueError:
                return None
            if first.get("op") == "version" and first["v"] != STATE_VERSION:
                return None
        tail: list[dict] = []
        cut = len(lines)                    # index of the cut-point line
        floor_rv = None     # rvs must strictly DECREASE walking backward
        for i in range(len(lines) - 1, -1, -1):
            try:
                rec = json.loads(lines[i])
            except ValueError:
                return None                 # mid-file corruption: full load
            op = rec["op"]
            if op in ("version", "rotated"):
                continue
            if op == "epoch":
                tail.append(rec)
                continue
            if op == "put":
                rv = int(rec["data"]["meta"]["resource_version"])
            elif op == "delete":
                if "rv" not in rec:
                    return None             # pre-HA record: no stamp
                rv = int(rec["rv"])
            else:
                continue
            if floor_rv is not None and rv >= floor_rv:
                # Appends are rv-ordered under the store lock; a
                # non-monotonic tail means a zombie leader appended
                # through a stale handle (its rv counter rewound). The
                # cut-point heuristic cannot be trusted against that —
                # a zombie's low rv would masquerade as the mirrored
                # boundary and silently drop the real leader's
                # unmirrored records. Full load handles zombies via
                # the in-order epoch fence.
                return None
            floor_rv = rv
            if rv <= warm_rv:
                cut = i
                break                       # everything earlier is mirrored
            tail.append(rec)
        if cut < len(lines):
            # Validate the cut itself: the nearest preceding OBJECT
            # record must carry a smaller rv, or the "cut" is a zombie
            # append at the very end of the file (the commonest zombie
            # shape) masquerading as the mirrored boundary.
            cut_rv = floor_rv
            for i in range(cut - 1, -1, -1):
                try:
                    rec = json.loads(lines[i])
                except ValueError:
                    return None
                op = rec["op"]
                if op == "put":
                    prev_rv = int(rec["data"]["meta"]["resource_version"])
                elif op == "delete":
                    prev_rv = int(rec.get("rv", 0))
                else:
                    continue
                if prev_rv >= cut_rv:
                    return None             # rv rewound at the cut
                break
        tail.reverse()
        objects = dict(warm)
        max_rv = warm_rv
        # The fencing epoch IN EFFECT at the cut point, so the tail's
        # zombie-drop rule evolves in log order exactly as load()'s
        # does (seeding from the sidecar — the LATEST bump — would drop
        # legitimate records written before a bump that sits later in
        # the tail). Epoch records before the cut are found by a
        # string-prefix scan; their payloads never need decoding.
        epoch = snap_epoch
        for i in range(cut - 1, -1, -1):
            if lines[i].startswith(_EPOCH_LINE_PREFIX):
                try:
                    epoch = max(epoch, int(json.loads(lines[i])["epoch"]))
                except (ValueError, KeyError, TypeError):
                    pass
                break                       # latest bump before the cut
        for rec in tail:
            if rec["op"] == "epoch":
                epoch = max(epoch, int(rec["epoch"]))
                continue
            if int(rec.get("e", epoch)) < epoch:
                continue                    # zombie append (see load())
            if rec["op"] == "put":
                cls = registry.get(rec["kind"])
                if cls is None:
                    continue
                obj = from_dict(cls, rec["data"])
                objects[(rec["kind"], obj.meta.namespace,
                         obj.meta.name)] = obj
                max_rv = max(max_rv, obj.meta.resource_version)
            else:
                objects.pop((rec["kind"], rec["ns"], rec["name"]), None)
                max_rv = max(max_rv, int(rec["rv"]))
        self._wal_records = len(lines)
        # The sidecar (rewritten on every bump, fsynced) backstops the
        # final term — e.g. a bump whose WAL record sits in a rotated
        # segment this path refused to read.
        epoch = max(epoch, self._sidecar_epoch())
        self.last_load = {"mode": "warm", "decoded": len(tail) + 1,
                          "lines": len(lines), "snapshot_objects": 0}
        return list(objects.values()), max_rv, epoch

    # ---- append ----------------------------------------------------------

    def _append(self, record: dict, fsync: bool = False) -> None:
        if self._wal_file is None:
            fresh = (not os.path.exists(self.wal_path)
                     or os.path.getsize(self.wal_path) == 0)
            self._wal_file = open(self.wal_path, "a")
            if fresh:
                # Fresh WAL leads with its schema version: a WAL-only
                # state dir (no snapshot yet) must still refuse to load
                # in an older build.
                self._wal_file.write(json.dumps(
                    {"op": "version", "v": STATE_VERSION}) + "\n")
        self._wal_file.write(json.dumps(record) + "\n")
        self._wal_file.flush()
        if fsync:
            os.fsync(self._wal_file.fileno())
        self._wal_records += 1

    def record_put(self, obj: Any, epoch: int = 0) -> None:
        self._append({"op": "put", "kind": obj.KIND, "e": epoch,
                      "data": to_dict(obj)})

    def record_delete(self, obj: Any, rv: int = 0, epoch: int = 0) -> None:
        # ``rv`` is the deletion's own seq (the store allocates one per
        # delete): the warm-start tail scan needs every record rv-
        # addressable, and replaying an unstamped delete over a mirror
        # could remove a later re-creation it never should have seen.
        self._append({"op": "delete", "kind": obj.KIND,
                      "ns": obj.meta.namespace, "name": obj.meta.name,
                      "rv": rv, "e": epoch})

    def record_epoch(self, epoch: int) -> None:
        """Persist a fencing-epoch bump: an fsynced WAL record (the
        bump IS the fence — it must be durable before the new leader's
        first write) plus the sidecar rewrite for the warm loader."""
        self._append({"op": "epoch", "epoch": epoch}, fsync=True)
        tmp = f"{self.epoch_path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps({"epoch": epoch}))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.epoch_path)
        except OSError:
            pass          # sidecar is a fast-path hint; WAL is truth

    # ---- compaction ------------------------------------------------------

    def maybe_compact(self, objects: list[Any], rv: int,
                      epoch: int = 0) -> bool:
        """Fold the WAL into a snapshot once it exceeds the threshold.
        Caller passes a consistent view (holds the store lock). The
        expensive half — serializing every object — runs in a
        BACKGROUND thread; only the WAL rotation (footer + fsync +
        rename + fresh file) happens on the write path, so a large
        fleet's writers never stall behind an O(objects) json.dump."""
        if self._wal_records < self.compact_every:
            return False
        if self._compact_thread is not None \
                and self._compact_thread.is_alive():
            return False                    # one compaction at a time
        if os.path.exists(self.segment_path):
            # A leftover segment (crashed compaction that load() didn't
            # see — e.g. the crash was ours, mid-run) folds
            # synchronously: rotating a second segment on top would
            # need an ordered chain nothing replays.
            self.compact(objects, rv, epoch)
            return True
        if not self.compact_async:
            self.compact(objects, rv, epoch)
            return True
        self._rotate_wal(rv)
        import threading
        self._compact_thread = threading.Thread(
            target=self._finish_compaction, args=(list(objects), rv, epoch),
            name="wal-compact", daemon=True)
        self._compact_thread.start()
        return True

    def _rotate_wal(self, rv: int) -> None:
        """Seal the live WAL as the compacting segment (caller holds
        the store lock): footer record naming the view rv, fsync so the
        footer survives the rename, rename, reset. The next append
        opens a fresh WAL with its own version header."""
        if self._wal_file is None:
            self._wal_file = open(self.wal_path, "a")
        self._wal_file.write(json.dumps({"op": "rotated", "rv": rv}) + "\n")
        self._wal_file.flush()
        os.fsync(self._wal_file.fileno())
        self._wal_file.close()
        self._wal_file = None
        os.replace(self.wal_path, self.segment_path)
        self._wal_records = 0

    def _finish_compaction(self, objects: list[Any], rv: int,
                           epoch: int) -> None:
        """Background half: write the snapshot durably, then drop the
        folded segment. Object references are immutable per version
        (the store replaces, never mutates), so serializing outside
        the lock is race-free."""
        try:
            self._write_snapshot(objects, rv, epoch)
            os.unlink(self.segment_path)
        except OSError:
            pass      # load() folds a leftover segment on next boot

    def _write_snapshot(self, objects: list[Any], rv: int,
                        epoch: int) -> None:
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            # Header keys first and in this order: the warm loader
            # reads "rv" from the file head without a full parse.
            json.dump({"version": STATE_VERSION, "rv": rv,
                       "epoch": epoch,
                       "objects": [{"kind": o.KIND, "data": to_dict(o)}
                                   for o in objects]}, f)
            f.flush()
            os.fsync(f.fileno())
        # Never regress the snapshot: the test harness's simulated
        # restarts run sequential Store instances over one dir in ONE
        # process (they share the flock), so an abandoned instance's
        # still-running background compaction could otherwise rename an
        # OLDER view over the successor's newer one. Checked right
        # before the rename to shrink the window to the rename itself;
        # cross-process this cannot happen (the flock serializes, and a
        # dead process has no background thread).
        try:
            with open(self.snapshot_path, "rb") as f:
                m = _SNAP_RV_RE.search(f.read(256).decode(errors="replace"))
            if m is not None and int(m.group(1)) > rv:
                os.unlink(tmp)
                return
        except OSError:
            pass
        os.replace(tmp, self.snapshot_path)
        # Directory fsync: the rename itself must survive a power cut,
        # or load() could see the OLD snapshot next to a truncated WAL.
        try:
            dfd = os.open(self.state_dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass

    def compact(self, objects: list[Any], rv: int, epoch: int = 0) -> None:
        """Synchronous compaction (load-time upgrades, leftover-segment
        folds, tests): snapshot durably, then truncate WAL + segment."""
        self.join_compaction()
        self._write_snapshot(objects, rv, epoch)
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = None
        open(self.wal_path, "w").close()
        try:
            os.unlink(self.segment_path)
        except OSError:
            pass
        self._wal_records = 0

    def join_compaction(self, timeout: float = 10.0) -> None:
        """Wait out an in-flight background compaction (tests, close)."""
        t = self._compact_thread
        if t is not None and t.is_alive():
            t.join(timeout)

    def close(self) -> None:
        self.join_compaction()
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = None
