"""Write-path telemetry for the store — the deploy path's eyes.

The read side has had instrumentation since the informer layer
(`grove_informer_*`, `Store.list_scans`); this module gives every store
WRITE the same treatment, because the 1000-pod deploy path is
write-bound (ROADMAP item 1): before batching or sharding the write
path we need to see who writes what, how often a write conflicts or
no-ops, and how long writers wait on (and hold) the store's global
RLock.

Exported series (rendered by the shared MetricsHub):

- ``grove_store_writes_total{kind,verb,writer}`` — committed mutations
  (a cascade delete counts one ``delete`` per removed object; a status
  write suppressed as a no-op counts under ``_noop_``, not here).
- ``grove_store_conflicts_total{kind,verb,writer}`` — optimistic-
  concurrency rejections (stale resource_version).
- ``grove_store_fenced_writes_total{kind,verb,writer}`` — writes
  rejected by the leadership fence (writer epoch older than the
  store's, grove_tpu/ha): a deposed leader's zombie writes, made
  visible.
- ``grove_store_noop_writes_total{kind,writer}`` — suppressed
  byte-identical status writes (the steady-state self-trigger guard).
- ``grove_store_events_total{kind,type}`` — event-ring appends (the
  fan-out cost every write pays: each append wakes every watcher).
- ``grove_store_lock_wait_seconds{verb}`` /
  ``grove_store_lock_hold_seconds{verb}`` — pinned-bucket histograms
  around the store RLock per public write verb (wait = acquisition
  queueing, i.e. writer contention; hold = critical-section length,
  i.e. what everyone else waited for). Observed only for records that
  committed, conflicted, or emitted — a PURE no-op status write (the
  steady-state self-trigger guard firing, i.e. every reconcile of a
  converged fleet) counts only its no-op counter, because per-write
  histogram bookkeeping on that path measurably erodes the PR 2
  informer steady-sweep ratio the issue requires to hold.
- ``grove_store_list_scans_total{kind}`` — the metric twin of
  ``Store.list_scans`` so benches and dashboards read exposition text
  instead of poking store internals.

Writer attribution rides a contextvar: the controller runtime sets it
to the controller's name for the duration of each reconcile
(``runtime/controller.py``), so a write deep inside a reconcile is
labeled ``writer="podclique"`` without threading a parameter through
every call. Unattributed writes (user clients, agents, tests) label
``writer="direct"``.

Overhead discipline: the store's write verbs buffer their telemetry in
a per-thread record while the store lock is held and flush it to the
hub in ONE lock acquisition after release (``MetricsHub.bulk``) — the
hub's lock is held across every /metrics render, and per-counter incs
under the store lock would stall all writers behind each scrape. The
PR 1/2 benchmarks must hold: ``GROVE_WRITE_OBS=0`` is the escape hatch
(per-call check, flippable at runtime), and the on-path cost is bounded
by tests/test_observability.py's overhead benchmark.
"""

from __future__ import annotations

import contextvars
import os
import threading

from grove_tpu.runtime.metrics import GLOBAL_METRICS

WRITE_OBS_ENV = "GROVE_WRITE_OBS"

# Label for writes outside any attributed context (user clients, node
# agents, scheduler runnables, tests).
DIRECT_WRITER = "direct"

_writer_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "grove_store_writer", default=DIRECT_WRITER)

# Per-sweep attribution sink (runtime/sweepobs.py): a contextvar — NOT
# a thread-local — because reconcile fan-out through
# runtime/concurrent.py copies the submitter's context onto pool
# threads; a slow-start pod-creation burst's writes must land in the
# sweep that issued them, exactly like the writer label above. The sink
# object itself is thread-safe (many pool threads absorb into one).
_sweep_sink_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "grove_sweep_sink", default=None)

# The write record being accumulated by this thread's in-flight store
# write verb (the store lock serializes writers, but records are
# per-thread so concurrent verbs on different stores never mix).
_active = threading.local()


def enabled() -> bool:
    """Read the escape hatch per call (the GROVE_INFORMER idiom):
    flipping ``GROVE_WRITE_OBS=0`` mid-process — incident mitigation,
    the overhead benchmark's baseline — takes effect on the next
    write, no store rebuild."""
    return os.environ.get(WRITE_OBS_ENV, "1") != "0"


def set_writer(name: str):
    """Attribute subsequent writes on this context to ``name`` (the
    controller runtime calls this per reconcile). Returns a token for
    ``reset_writer``."""
    return _writer_ctx.set(name)


def reset_writer(token) -> None:
    _writer_ctx.reset(token)


def current_writer() -> str:
    return _writer_ctx.get()


def set_sweep_sink(sink):
    """Install a per-sweep write sink on this context (the sweep
    observatory calls this around each reconcile). Every WriteRecord
    flushed while it is installed — on this thread or any pool thread
    the context is copied onto — is absorbed into the sink. Returns a
    token for ``reset_sweep_sink``."""
    return _sweep_sink_ctx.set(sink)


def reset_sweep_sink(token) -> None:
    _sweep_sink_ctx.reset(token)


def current_sweep_sink():
    return _sweep_sink_ctx.get()


class WriteRecord:
    """Telemetry buffered across one public store write verb."""

    __slots__ = ("verb", "writer", "commits", "noops", "conflicts",
                 "fenced", "events", "scans", "wait_s", "hold_s")

    def __init__(self, verb: str, writer: str) -> None:
        self.verb = verb
        self.writer = writer
        self.commits: list[tuple[str, str]] = []    # (kind, verb)
        self.noops: list[str] = []                  # kind
        self.conflicts: list[tuple[str, str]] = []  # (kind, verb)
        self.fenced: list[tuple[str, str]] = []     # (kind, verb)
        self.events: list[tuple[str, str]] = []     # (kind, type)
        self.scans: list[str] = []                  # kind (reentrant lists)
        self.wait_s = 0.0
        self.hold_s = 0.0


def begin(verb: str) -> WriteRecord | None:
    """Open a record for a public write verb (None when disabled).
    The caller must ``flush`` it after releasing the store lock."""
    if not enabled():
        return None
    rec = WriteRecord(verb, _writer_ctx.get())
    _active.rec = rec
    return rec


# ---- in-flight notes (called under the store lock; list appends only,
# ---- never the metrics hub) ----

def _rec() -> WriteRecord | None:
    return getattr(_active, "rec", None)


def note_commit(kind: str, verb: str) -> None:
    rec = _rec()
    if rec is not None:
        rec.commits.append((kind, verb))


def note_noop(kind: str) -> None:
    rec = _rec()
    if rec is not None:
        rec.noops.append(kind)


def note_conflict(kind: str, verb: str) -> None:
    rec = _rec()
    if rec is not None:
        rec.conflicts.append((kind, verb))


def note_fenced(kind: str, verb: str) -> None:
    """A write rejected by the leadership fence (stale writer epoch —
    grove_tpu/ha): counted into ``grove_store_fenced_writes_total`` so
    a deposed leader's rejected writes are visible evidence, not a
    silent exception path."""
    rec = _rec()
    if rec is not None:
        rec.fenced.append((kind, verb))


def note_event(kind: str, etype: str) -> None:
    rec = _rec()
    if rec is not None:
        rec.events.append((kind, etype))


# Cached (name, labels, 1.0) inc triples and label tuples, keyed by
# their label values. Label tuples are hand-ordered alphabetically (the
# hub's sorted-items key). Cardinality is kinds x verbs x writers —
# small and bounded — and caching spares the hot path a fresh nest of
# tuples per sample: a reconcile sweep of a converged 256-pod fleet is
# ~400 no-op status writes, and per-write allocation cost there erodes
# the PR 2 informer steady-sweep ratio.
_WRITE_INC: dict[tuple, tuple] = {}
_NOOP_INC: dict[tuple, tuple] = {}
_CONFLICT_INC: dict[tuple, tuple] = {}
_FENCED_INC: dict[tuple, tuple] = {}
_EVENT_INC: dict[tuple, tuple] = {}
_VERB_LABELS: dict[str, tuple] = {}


def _cached(cache: dict, key: tuple, name: str, labels: tuple) -> tuple:
    inc = cache.get(key)
    if inc is None:
        inc = cache[key] = (name, labels, 1.0)
    return inc


_SCAN_INC: dict[str, tuple] = {}


def count_scan(kind: str) -> None:
    """One list-shaped scan of ``kind`` into
    ``grove_store_list_scans_total`` (cached key; called outside the
    store lock on every Store.list/list_snapshot — the direct-read
    escape hatch path pays this thousands of times per sweep).

    When this thread has a write record open, the scan came from a
    REENTRANT list inside a write verb (the admission chain listing
    nodes under ``_locked_write``) and the store RLock is still held —
    so the inc is buffered into the record and flushed with everything
    else after release, instead of taking the hub lock under the store
    lock (the GROVE_LOCKDEP=1 witness caught exactly this edge on the
    create path)."""
    if not enabled():
        return
    rec = _rec()
    if rec is not None:
        rec.scans.append(kind)
        return
    sink = _sweep_sink_ctx.get()
    if sink is not None:
        # Scans inside an open write record reach the sweep sink at
        # flush; this is the common standalone-list path.
        sink.absorb_scan(kind)
    inc = _SCAN_INC.get(kind)
    if inc is None:
        inc = _SCAN_INC[kind] = (
            "grove_store_list_scans_total", (("kind", kind),), 1.0)
    GLOBAL_METRICS.bulk(incs=(inc,))


def flush(rec: WriteRecord) -> None:
    """Fold the record into the global hub under ONE hub-lock
    acquisition. Runs after the store lock is released. A pure no-op
    record (suppressed status write, nothing committed) takes a minimal
    path — one cached-key counter inc, no lock histograms — because it
    IS the steady state: every reconcile of a converged fleet ends in
    exactly one of these."""
    _active.rec = None
    sink = _sweep_sink_ctx.get()
    if sink is not None:
        # Sweep attribution (runtime/sweepobs.py) — fed on EVERY path,
        # pure no-ops included: "how many write calls did this sweep
        # issue" is exactly the number batching is supposed to bend.
        sink.absorb(rec)
    w = rec.writer
    if not rec.commits and not rec.conflicts and not rec.events \
            and not rec.fenced:
        if rec.noops or rec.scans:
            GLOBAL_METRICS.bulk(incs=[
                _cached(_NOOP_INC, (kind, w),
                        "grove_store_noop_writes_total",
                        (("kind", kind), ("writer", w)))
                for kind in rec.noops] + [
                _cached(_SCAN_INC, kind,
                        "grove_store_list_scans_total",
                        (("kind", kind),))
                for kind in rec.scans])
        return
    incs: list[tuple[str, tuple, float]] = []
    for kind in rec.scans:
        incs.append(_cached(
            _SCAN_INC, kind, "grove_store_list_scans_total",
            (("kind", kind),)))
    for kind, verb in rec.commits:
        incs.append(_cached(
            _WRITE_INC, (kind, verb, w), "grove_store_writes_total",
            (("kind", kind), ("verb", verb), ("writer", w))))
    for kind in rec.noops:
        incs.append(_cached(
            _NOOP_INC, (kind, w), "grove_store_noop_writes_total",
            (("kind", kind), ("writer", w))))
    for kind, verb in rec.conflicts:
        incs.append(_cached(
            _CONFLICT_INC, (kind, verb, w),
            "grove_store_conflicts_total",
            (("kind", kind), ("verb", verb), ("writer", w))))
    for kind, verb in rec.fenced:
        incs.append(_cached(
            _FENCED_INC, (kind, verb, w),
            "grove_store_fenced_writes_total",
            (("kind", kind), ("verb", verb), ("writer", w))))
    for kind, etype in rec.events:
        incs.append(_cached(
            _EVENT_INC, (kind, etype), "grove_store_events_total",
            (("kind", kind), ("type", etype))))
    verb_labels = _VERB_LABELS.get(rec.verb)
    if verb_labels is None:
        verb_labels = _VERB_LABELS[rec.verb] = (("verb", rec.verb),)
    GLOBAL_METRICS.bulk(
        incs=incs,
        observations=(
            ("grove_store_lock_wait_seconds", verb_labels, rec.wait_s),
            ("grove_store_lock_hold_seconds", verb_labels, rec.hold_s),
        ))
