from grove_tpu.store.store import Event, EventType, Store, Watcher
from grove_tpu.store.client import Client, FakeClient

__all__ = ["Event", "EventType", "Store", "Watcher", "Client", "FakeClient"]
