"""Cluster assembly: one call brings up a full in-process control plane.

The `cmd/main.go` analog (R1): config → manager → scheduler registry →
controllers → agents. Used by the CLI, the e2e tests, and the scale
harness; a real deployment runs exactly this plus process-running node
agents instead of (or alongside) the fake kubelet pool.
"""

from __future__ import annotations

import dataclasses
import weakref

from grove_tpu.agent.node import FakeKubeletPool
from grove_tpu.api.config import OperatorConfiguration
from grove_tpu.controllers.register import register_controllers
from grove_tpu.runtime.manager import Manager
from grove_tpu.scheduler.framework import Registry
from grove_tpu.store.client import Client
from grove_tpu.store.store import Store
from grove_tpu.topology.fleet import FleetSpec, create_fleet


# Live started clusters, weakly held: diagnostics collectors (the e2e
# on-failure bundle, tests/diagnostics.py — reference
# e2e/diagnostics/collector.go analog) enumerate these to dump state
# without the test having to thread its cluster to the hook.
_LIVE: "weakref.WeakSet[Cluster]" = weakref.WeakSet()


def live_clusters() -> "list[Cluster]":
    return list(_LIVE)


# eq=False keeps identity hashing (dataclass __eq__ would drop __hash__,
# and the live-cluster WeakSet needs hashable entries).
@dataclasses.dataclass(eq=False)
class Cluster:
    manager: Manager
    scheduler_registry: Registry
    metrics: "MetricsRegistry | None" = None

    @property
    def client(self) -> Client:
        return self.manager.client

    def start(self) -> None:
        self.manager.start()
        _LIVE.add(self)

    def stop(self) -> None:
        _LIVE.discard(self)
        self.manager.stop()

    def __enter__(self) -> "Cluster":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def new_cluster(config: OperatorConfiguration | None = None,
                fleet: FleetSpec | None = None,
                store: Store | None = None,
                fake_kubelet: bool = True,
                admission: bool = True,
                state_dir: str | None = None,
                state_takeover: bool = False) -> Cluster:
    """``state_dir`` enables durable control-plane state (WAL + snapshot,
    store/persist.py): a restarted cluster pointed at the same directory
    resumes with every resource intact and reconciles from there —
    restart is free, as with the reference's etcd. ``create_fleet`` is
    idempotent, so passing the same ``fleet`` on reboot is safe.

    The state dir is single-writer (flock; the leader-election analog,
    reference manager.go:55-147): a second cluster on the same dir
    raises ``StateLockError``, or with ``state_takeover=True`` blocks as
    a standby until the holder exits, then loads and takes over."""
    if store is None and state_dir is not None:
        store = Store(state_dir=state_dir, takeover_wait=state_takeover)
    mgr = Manager(config=config, store=store)
    registry = register_controllers(mgr)
    # Configuring API tokens implies wanting their identities enforced —
    # a user token that the authorizer never checks would be a silent
    # no-op (every mapped actor could mutate managed children).
    if mgr.config.server_auth.tokens and not mgr.config.authorizer.enabled:
        mgr.config.authorizer.enabled = True
    if admission:
        from grove_tpu.admission import install_admission
        install_admission(mgr.store, mgr.config, registry)
    if fake_kubelet:
        mgr.add_runnable(FakeKubeletPool(mgr.client))
    metrics = None
    if mgr.config.autoscaler.enabled:
        from grove_tpu.autoscale import Autoscaler, MetricsRegistry
        from grove_tpu.runtime.servingwatch import ServingObserver
        metrics = MetricsRegistry()
        # Writer runnables take the manager's LEADER client so a
        # leadership transition fences their writes (grove_tpu/ha);
        # read-only observers and the kubelet pool stay on mgr.client.
        mgr.add_runnable(Autoscaler(
            mgr.leader_client, metrics,
            sync_period=mgr.config.autoscaler.sync_period_seconds,
            scale_down_stabilization=mgr.config.autoscaler
            .scale_down_stabilization_seconds))
        # Serving observatory: aggregates the registry's engine-pushed
        # SLO signals into grove_serving_* gauges and /debug/serving
        # (rides the autoscaler flag — both consume the same registry).
        # Swept at the autoscaler's own cadence: each sweep lists three
        # kinds off the store, and the signals it judges only move when
        # engines push, so out-sweeping the consumer buys no freshness.
        mgr.add_runnable(ServingObserver(
            mgr.client, metrics, mgr.store,
            tick=mgr.config.autoscaler.sync_period_seconds))
    if mgr.config.defrag.enabled:
        # Active placement repair (ROADMAP item 2): consumes the explain
        # diagnoses and migrates gangs to consolidate fragmented free
        # capacity; GROVE_DEFRAG=0 no-ops every sweep without rewiring.
        from grove_tpu.defrag import DefragController
        mgr.add_runnable(DefragController(
            mgr.leader_client, mgr.store, mgr.config.defrag,
            disruption_deadline_s=mgr.config.disruption
            .default_deadline_seconds,
            barriers_enabled=mgr.config.disruption.enabled))
    if mgr.config.disruption.enabled:
        # Spot-slice reclamation + disruption-contract coordination
        # (ROADMAP items 3/5): evacuates gangs off reclaim-noticed
        # capacity behind the checkpoint barrier and drives registered
        # checkpoint responders for every planned eviction's notice.
        # GROVE_DISRUPTION=0 strips the barriers without rewiring.
        from grove_tpu.disruption.reclaim import ReclaimController
        mgr.add_runnable(ReclaimController(mgr.leader_client, mgr.store,
                                           mgr.config.disruption))
    if mgr.config.ha.enabled:
        # HA leadership (grove_tpu/ha): the elector campaigns at
        # manager start — epoch bump, writer fencing, /debug/leadership
        # live. Off by default: a single-replica start keeps the exact
        # pre-HA shape (epoch 0, clients unfenced).
        from grove_tpu.ha.election import LeaderElector
        mgr.add_runnable(LeaderElector(mgr, state_dir=state_dir))
    if mgr.config.node_lifecycle.enabled:
        from grove_tpu.controllers.nodelifecycle import (
            NodeLifecycleController,
        )
        mgr.add_runnable(NodeLifecycleController(
            mgr.leader_client,
            grace_seconds=mgr.config.node_lifecycle.grace_seconds,
            sync_period=mgr.config.node_lifecycle.sync_period_seconds))
    if fleet is not None:
        create_fleet(mgr.client, fleet)
    return Cluster(manager=mgr, scheduler_registry=registry, metrics=metrics)
