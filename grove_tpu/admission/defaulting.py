"""Defaulting admission — fills omitted PodCliqueSet fields.

Role parity with reference admission/pcs/defaulting/podcliqueset.go
(912 LoC): replicas, min_available, startup type, termination delay (4h),
scheduler profile. TPU-first default: a template whose cliques request
chips gets required slice packing unless the user says otherwise — on
TPU, a gang that straddles slices cannot form ICI collectives, so
"packed" is the only sane default.
"""

from __future__ import annotations

from grove_tpu.api import constants as c
from grove_tpu.api.podcliqueset import (
    HeadlessServiceConfig,
    PodCliqueSet,
    TopologyConstraint,
    effective_startup_type,
)


def default_podcliqueset(pcs: PodCliqueSet) -> PodCliqueSet:
    spec = pcs.spec
    if spec.replicas < 1:
        spec.replicas = 1
    tmpl = spec.template
    if tmpl.startup_type is None:
        tmpl.startup_type = effective_startup_type(tmpl)
    if tmpl.termination_delay_seconds is None:
        tmpl.termination_delay_seconds = c.DEFAULT_TERMINATION_DELAY_SECONDS
    if tmpl.headless_service is None:
        tmpl.headless_service = HeadlessServiceConfig()
    uses_tpu = any(t.tpu_chips_per_pod > 0 for t in tmpl.cliques)
    if tmpl.topology is None and uses_tpu:
        tmpl.topology = TopologyConstraint(pack_level="slice", required=True)
    # Semantic inference (reference defaulting podcliqueset.go:80,97):
    # an autoscaler without an explicit floor never scales below the
    # declared steady-state replicas. Contradictory bounds are NOT
    # silently repaired — validation rejects them uniformly.
    if spec.auto_scaling is not None \
            and spec.auto_scaling.min_replicas is None:
        spec.auto_scaling.min_replicas = spec.replicas
    for t in tmpl.cliques:
        if t.replicas < 1:
            t.replicas = 1
        if t.auto_scaling is not None and t.auto_scaling.min_replicas is None:
            t.auto_scaling.min_replicas = t.replicas
        if t.min_available is None:
            # Autoscaled cliques default their gang floor to the scaling
            # floor (so scale-in below the initial replica count works);
            # fixed cliques default to all-replicas-required.
            if t.auto_scaling is not None:
                t.min_available = max(1, min(t.auto_scaling.min_replicas,
                                             t.replicas))
            else:
                t.min_available = t.replicas
    for sg in tmpl.scaling_groups:
        if sg.replicas < 1:
            sg.replicas = 1
        if sg.auto_scaling is not None \
                and sg.auto_scaling.min_replicas is None:
            sg.auto_scaling.min_replicas = sg.replicas
        if sg.min_available is None:
            sg.min_available = 1  # one gang-guaranteed instance; rest elastic
    return pcs
