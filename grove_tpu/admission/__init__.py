from grove_tpu.admission.chain import AdmissionChain, install_admission
from grove_tpu.admission.defaulting import default_podcliqueset
from grove_tpu.admission.validation import validate_podcliqueset

__all__ = [
    "AdmissionChain",
    "install_admission",
    "default_podcliqueset",
    "validate_podcliqueset",
]
