"""Validation admission — the PodCliqueSet rule set.

Role parity with reference admission/pcs/validation/ (6,289 LoC across 13
files), the rules that shape every downstream object:

- structural: names, replica/min_available bounds, uniqueness
- container: argv/env/workdir/readiness-probe shape, reserved env-var
  protection (the injected TPU/GROVE contract must not be overridden)
- name budgets: worst-case GENERATED child names (pod/service/gang) must
  fit the DNS-label limit — a valid user name can still compose into an
  invalid pod name (reference checks generated-name lengths the same way)
- chips: per-pod chip counts must be achievable on a real TPU host, and
  slice-packed gangs must fit a physically possible slice
  (topology/tpu.py generations)
- startup DAG: StartsAfter references exist and form a DAG (cycle
  detection via Tarjan SCC, reference podcliquedeps.go:53)
- topology: levels must exist in the hierarchy; child constraints must be
  at least as strict as the parent's (reference topologyconstraints.go)
- scaling groups: member cliques exist, belong to exactly one group,
  scale only through the group (no per-member autoscaling)
- update immutability: an explicit field table (reference
  podcliqueset.go:662-698), plus clique-set/SG-membership structure
- scheduler-specific checks via Backend.validate_pcs
"""

from __future__ import annotations

import re

from grove_tpu.api import constants as c
from grove_tpu.api.clustertopology import ClusterTopology, DEFAULT_TPU_LEVELS
from grove_tpu.api.core import ContainerSpec
from grove_tpu.api.podcliqueset import (PodCliqueSet, StartupType,
                                        TopologyConstraint)
from grove_tpu.api.reservation import ReservationScope
from grove_tpu.scheduler.framework import Registry
from grove_tpu.topology.tpu import TPU_GENERATIONS

_NAME_RE = re.compile(r"^[a-z0-9]([a-z0-9-]{0,50}[a-z0-9])?$")
_ENV_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")

# Generated child names are DNS labels (hostnames in the headless
# service); k8s caps those at 63 characters.
MAX_GENERATED_NAME = 63

# Env vars the controllers inject (controllers/podclique.py _add_env +
# the node agent). User env overriding these would silently break rank
# identity / discovery inside the pod. Only these EXACT names are
# reserved — TPU_* runtime tuning flags and user GROVE_*-prefixed vars
# of their own invention stay usable.
_RESERVED_ENV = frozenset({
    c.ENV_PCS_NAME, c.ENV_PCS_INDEX, c.ENV_PCLQ_NAME,
    c.ENV_PCLQ_POD_INDEX, c.ENV_PCSG_NAME, c.ENV_PCSG_INDEX,
    c.ENV_PCSG_TEMPLATE_NUM_PODS, c.ENV_HEADLESS_SERVICE,
    c.ENV_TPU_WORKER_ID, c.ENV_TPU_WORKER_HOSTNAMES,
    c.ENV_TPU_SLICE_NAME, c.ENV_TPU_SLICE_TOPOLOGY,
    c.ENV_MEGASLICE_INDEX, c.ENV_MEGASLICE_COUNT, c.ENV_RESERVATION,
    "GROVE_POD_NAME", "GROVE_NAMESPACE", "GROVE_NODE_NAME",
    "GROVE_CONTROL_PLANE",
})

_LEVELS = [lvl.domain for lvl in DEFAULT_TPU_LEVELS]  # outer -> inner


def tarjan_sccs(graph: dict[str, list[str]]) -> list[list[str]]:
    """Tarjan strongly-connected components (iterative)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(root: str) -> None:
        work = [(root, iter(graph.get(root, ())))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in graph:
                    continue
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(graph.get(w, ()))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                sccs.append(scc)

    for node in graph:
        if node not in index:
            strongconnect(node)
    return sccs


def _validate_topology(field: str, topo: TopologyConstraint | None,
                       parent: TopologyConstraint | None,
                       errs: list[str],
                       levels: list[str] | None = None,
                       resolve: bool = True) -> None:
    """Constraint levels must RESOLVE against the topology hierarchy the
    scheduler actually uses (reference validateResolvableTopologyConstraint,
    validation/podcliqueset.go:774: constraints are checked against the
    bound ClusterTopology's levels, not a hard-coded set). ``levels`` is
    the active CT's outer→inner domain list; None falls back to the
    built-in TPU hierarchy. ``resolve=False`` (updates) skips the
    resolution errors — topology fields are immutable on update, so
    re-resolving an unchanged constraint against a possibly-changed CT
    could only brick the object; strictness comparison still runs when
    both levels are known."""
    lv = levels if levels else _LEVELS

    def idx(level: str) -> int:
        return lv.index(level)

    if topo is None:
        return
    if resolve:
        if topo.pack_level and topo.pack_level not in lv:
            errs.append(f"{field}.pack_level: level {topo.pack_level!r} "
                        "does not resolve against the cluster topology; "
                        f"levels: {lv}")
        if topo.spread_level and topo.spread_level not in lv:
            errs.append(f"{field}.spread_level: level "
                        f"{topo.spread_level!r} does not resolve against "
                        f"the cluster topology; levels: {lv}")
    if (parent is not None and parent.pack_level in lv
            and topo.pack_level in lv
            and idx(topo.pack_level) < idx(parent.pack_level)):
        # child packs at an outer (looser) level than the parent demands
        errs.append(
            f"{field}.pack_level {topo.pack_level!r} is looser than the "
            f"template constraint {parent.pack_level!r} (child must be at "
            "least as strict)")


def _is_int(v) -> bool:
    return isinstance(v, int) and not isinstance(v, bool)


def _is_num(v) -> bool:
    return (isinstance(v, (int, float)) and not isinstance(v, bool))


def _validate_shape(pcs: PodCliqueSet) -> list[str]:
    """Type-shape sanity pass. Specs decoded through serde always have
    the right types; direct Python construction (or a future decode bug)
    may not — admission must REJECT malformed shapes, never crash on
    them (proven by the fuzz tests). Returns errors; when non-empty the
    semantic rules are skipped (they assume these shapes)."""
    from grove_tpu.api.podcliqueset import (AutoScalingConfig,
                                            PodCliqueSetTemplate,
                                            PodCliqueTemplate,
                                            ScalingGroupConfig)
    errs: list[str] = []

    def bad(path, want, got):
        errs.append(f"{path}: expected {want}, got {type(got).__name__}")

    if not isinstance(pcs.meta.name, str):
        bad("metadata.name", "string", pcs.meta.name)
    spec = pcs.spec
    if not _is_int(spec.replicas):
        bad("spec.replicas", "integer", spec.replicas)
    if spec.auto_scaling is not None and \
            not isinstance(spec.auto_scaling, AutoScalingConfig):
        bad("spec.auto_scaling", "AutoScalingConfig", spec.auto_scaling)
    tmpl = spec.template
    if not isinstance(tmpl, PodCliqueSetTemplate):
        bad("spec.template", "PodCliqueSetTemplate", tmpl)
        return errs
    if not _is_int(tmpl.priority):
        bad("spec.template.priority", "integer", tmpl.priority)
    if tmpl.termination_delay_seconds is not None and \
            not _is_num(tmpl.termination_delay_seconds):
        bad("spec.template.termination_delay_seconds", "number",
            tmpl.termination_delay_seconds)
    if tmpl.startup_type is not None and \
            not isinstance(tmpl.startup_type, StartupType):
        bad("spec.template.startup_type", "StartupType",
            tmpl.startup_type)
    for field in ("priority_class", "scheduler_name"):
        if not isinstance(getattr(tmpl, field), str):
            bad(f"spec.template.{field}", "string", getattr(tmpl, field))
    if tmpl.topology is not None and \
            not isinstance(tmpl.topology, TopologyConstraint):
        bad("spec.template.topology", "TopologyConstraint", tmpl.topology)
    if not isinstance(tmpl.cliques, list):
        bad("spec.template.cliques", "list", tmpl.cliques)
        return errs
    if not isinstance(tmpl.scaling_groups, list):
        bad("spec.template.scaling_groups", "list", tmpl.scaling_groups)
        return errs

    def check_common(f, obj):
        if not isinstance(obj.name, str):
            bad(f"{f}.name", "string", obj.name)
        if not _is_int(obj.replicas):
            bad(f"{f}.replicas", "integer", obj.replicas)
        if obj.min_available is not None and not _is_int(obj.min_available):
            bad(f"{f}.min_available", "integer", obj.min_available)
        if obj.auto_scaling is not None:
            if not isinstance(obj.auto_scaling, AutoScalingConfig):
                bad(f"{f}.auto_scaling", "AutoScalingConfig",
                    obj.auto_scaling)
            else:
                a = obj.auto_scaling
                if (a.min_replicas is not None
                        and not _is_int(a.min_replicas)) \
                        or not _is_int(a.max_replicas):
                    bad(f"{f}.auto_scaling.min/max_replicas", "integers",
                        (a.min_replicas, a.max_replicas))
        if obj.topology is not None and \
                not isinstance(obj.topology, TopologyConstraint):
            bad(f"{f}.topology", "TopologyConstraint", obj.topology)

    for i, t in enumerate(tmpl.cliques):
        f = f"spec.template.cliques[{i}]"
        if not isinstance(t, PodCliqueTemplate):
            bad(f, "PodCliqueTemplate", t)
            continue
        check_common(f, t)
        if not _is_int(t.tpu_chips_per_pod):
            bad(f"{f}.tpu_chips_per_pod", "integer", t.tpu_chips_per_pod)
        if not isinstance(t.starts_after, list) or any(
                not isinstance(d, str) for d in t.starts_after):
            bad(f"{f}.starts_after", "list of strings", t.starts_after)
        if not isinstance(t.priority_class, str):
            bad(f"{f}.priority_class", "string", t.priority_class)
        if t.container is not None and \
                not isinstance(t.container, ContainerSpec):
            bad(f"{f}.container", "ContainerSpec", t.container)
    for i, sg in enumerate(tmpl.scaling_groups):
        f = f"spec.template.scaling_groups[{i}]"
        if not isinstance(sg, ScalingGroupConfig):
            bad(f, "ScalingGroupConfig", sg)
            continue
        check_common(f, sg)
        if not isinstance(sg.clique_names, list) or any(
                not isinstance(m, str) for m in sg.clique_names):
            bad(f"{f}.clique_names", "list of strings", sg.clique_names)
    return errs


def _validate_container(field: str, spec: ContainerSpec,
                        errs: list[str]) -> None:
    """Container shape rules (reference pod-template/container checks,
    reshaped for exec-style workloads: argv instead of image+command).

    An empty argv is legal — fake fleets (the KWOK analog) synthesise
    readiness without executing anything — but whatever IS declared must
    be executable as given.
    """
    if spec is None:
        errs.append(f"{field}: container must not be null")
        return
    if not isinstance(spec.argv, list):
        errs.append(f"{field}.argv must be a list of strings")
    else:
        items_ok = True
        for i, a in enumerate(spec.argv):
            if not isinstance(a, str) or a == "":
                errs.append(f"{field}.argv[{i}] must be a non-empty string "
                            f"(got {a!r})")
                items_ok = False
        if items_ok and spec.argv and not spec.argv[0].strip():
            errs.append(f"{field}.argv[0] (the executable) is blank")
    if not isinstance(spec.env, dict):
        errs.append(f"{field}.env must be a string map")
    else:
        for k in spec.env:
            if not isinstance(k, str) or not _ENV_RE.match(k):
                errs.append(f"{field}.env: invalid variable name {k!r}")
            elif k in _RESERVED_ENV:
                errs.append(
                    f"{field}.env: {k!r} is reserved (injected rank/"
                    "discovery contract); overriding it would break "
                    "multi-host bootstrap inside the pod")
            if not isinstance(spec.env.get(k), str):
                errs.append(f"{field}.env[{k!r}] must be a string")
    if not isinstance(spec.workdir, str):
        errs.append(f"{field}.workdir must be a string")
    elif spec.workdir and not spec.workdir.startswith("/"):
        errs.append(f"{field}.workdir must be an absolute path, got "
                    f"{spec.workdir!r}")
    if not isinstance(spec.readiness_file, str):
        errs.append(f"{field}.readiness_file must be a string")
    elif spec.readiness_file:
        parts = spec.readiness_file.split("/")
        if ".." in parts:
            errs.append(f"{field}.readiness_file must not contain '..' "
                        f"(path escape), got {spec.readiness_file!r}")
        if len(spec.readiness_file) > 4096:
            errs.append(f"{field}.readiness_file exceeds 4096 chars")
    # Probe timing bounds (k8s probe-field validation analog; the node
    # agent honors these — agent/process.py _probe_readiness).
    probe_declared = isinstance(spec.readiness_file, str) \
        and bool(spec.readiness_file)
    for pf, lo, hi in (("readiness_initial_delay_s", 0.0, 3600.0),
                       ("readiness_period_s", 0.05, 300.0),
                       ("readiness_timeout_s", 0.0, 86400.0)):
        v = getattr(spec, pf)
        if not _is_num(v):
            errs.append(f"{field}.{pf} must be a number")
            continue
        if pf == "readiness_timeout_s" and v == 0:
            continue                      # 0 = no deadline, always legal
        if not (lo <= v <= hi):
            errs.append(f"{field}.{pf} {v} outside [{lo}, {hi}]")
        if not probe_declared and v != ContainerSpec.__dataclass_fields__[
                pf].default:
            errs.append(f"{field}.{pf} set without readiness_file; probe "
                        "timing without a probe does nothing")
    if probe_declared and _is_num(spec.readiness_timeout_s) \
            and _is_num(spec.readiness_period_s) \
            and 0 < spec.readiness_timeout_s < spec.readiness_period_s:
        errs.append(f"{field}.readiness_timeout_s "
                    f"{spec.readiness_timeout_s} < readiness_period_s "
                    f"{spec.readiness_period_s}: the probe would time out "
                    "before its first check")


def _ratchet(old_obj, new_obj, *getters) -> bool:
    """True when a rule should be ENFORCED: on create (no old), or when
    an update touched the fields the rule reads. Rules added after
    objects were persisted must ratchet this way — re-validating an
    unchanged stanza under new rules would brick every subsequent
    update of a legally-admitted object (the k8s ratcheting-validation
    convention)."""
    if old_obj is None:
        return True
    return any(g(old_obj) != g(new_obj) for g in getters)


def _validate_autoscaling(field: str, a, replicas: int,
                          min_available, errs: list[str],
                          enforce_ceiling: bool = True) -> None:
    """Shared HPA-bounds rules (reference validateScaleConfig,
    validation/podcliqueset.go:573): floor >= 1, floor <= ceiling,
    ceiling >= declared replicas (an autoscaler whose max is below the
    steady state would fight the declared shape on its first pass —
    ratcheted via ``enforce_ceiling``), and floor >= the gang floor
    (scaling below min_available would permanently breach the gang).
    min_replicas may be None when validating a spec that has not been
    through defaulting admission — it then resolves to ``replicas``,
    matching the defaulting inference.
    """
    lo = a.min_replicas if a.min_replicas is not None else replicas
    if lo < 1:
        errs.append(f"{field}: auto_scaling.min_replicas must be >= 1")
    if lo > a.max_replicas:
        errs.append(f"{field}: auto_scaling min {lo} > max "
                    f"{a.max_replicas}")
    if enforce_ceiling and a.max_replicas < replicas:
        errs.append(f"{field}: auto_scaling.max_replicas "
                    f"{a.max_replicas} < replicas {replicas}; the "
                    "autoscaler would fight the declared steady state")
    if min_available is not None and lo < min_available:
        errs.append(f"{field}: auto_scaling.min_replicas must be >= "
                    "min_available (the gang floor)")


def _scaling_shape(obj):
    """Fields the autoscaling-ceiling rule reads (for ratcheting)."""
    return (obj.replicas,
            obj.auto_scaling.max_replicas if obj.auto_scaling else None)


def _digits(n: int) -> int:
    return len(str(max(0, n)))


def _clique_max_replicas(t) -> int:
    """Largest replica count a clique can reach (autoscaling ceiling)."""
    if t.auto_scaling is not None:
        return max(t.replicas, t.auto_scaling.max_replicas)
    return t.replicas


def _sg_max_replicas(sg) -> int:
    if sg.auto_scaling is not None:
        return max(sg.replicas, sg.auto_scaling.max_replicas)
    return sg.replicas


def _validate_name_budgets(pcs: PodCliqueSet, errs: list[str]) -> None:
    """Generated child names must fit the DNS-label budget at the WORST
    CASE the spec allows (max replica indices incl. autoscaling ceilings).

    A 52-char user name passes the name rule yet composes into
    <pcs>-<r>-<pcsg>-<j>-<clique>-<i> — validation must fail the create,
    not the first scale-out (reference validates generated-name budgets
    for the same reason).
    """
    tmpl = pcs.spec.template
    pcs_len = len(pcs.meta.name)
    max_pcs_replicas = pcs.spec.replicas
    if pcs.spec.auto_scaling is not None:
        # The service-level autoscaler scales spec.replicas to this.
        max_pcs_replicas = max(max_pcs_replicas,
                               pcs.spec.auto_scaling.max_replicas)
    r_digits = _digits(max_pcs_replicas - 1)
    in_group = {name: sg for sg in tmpl.scaling_groups
                for name in sg.clique_names}

    def check(what: str, length: int) -> None:
        if length > MAX_GENERATED_NAME:
            errs.append(
                f"{what} would generate a {length}-char name "
                f"(max {MAX_GENERATED_NAME}); shorten the PodCliqueSet/"
                "clique/scaling-group names or lower replica ceilings")

    # headless service: <pcs>-<r>-svc
    check("headless service", pcs_len + 1 + r_digits + 1 + 3)
    # workload token secret: <pcs>-workload-token
    check("workload token secret", pcs_len + 15)
    for t in tmpl.cliques:
        pod_digits = _digits(_clique_max_replicas(t) - 1)
        sg = in_group.get(t.name)
        if sg is None:
            # <pcs>-<r>-<clique>-<i>
            check(f"clique {t.name!r} pods",
                  pcs_len + 1 + r_digits + 1 + len(t.name) + 1 + pod_digits)
        else:
            j_digits = _digits(_sg_max_replicas(sg) - 1)
            # <pcs>-<r>-<sg>-<j>-<clique>-<i>
            check(f"clique {t.name!r} pods (in scaling group {sg.name!r})",
                  pcs_len + 1 + r_digits + 1 + len(sg.name) + 1 + j_digits
                  + 1 + len(t.name) + 1 + pod_digits)
    for rt in tmpl.reservations:
        # AllReplicas <pcs>-<rt>-rsv; PerReplica <pcs>-<r>-<rt>-rsv.
        # Also a node-label VALUE (LABEL_RESERVATION), same 63-char cap.
        length = pcs_len + 1 + len(rt.name) + 4
        if rt.scope == ReservationScope.PER_REPLICA:
            length += 1 + r_digits
        check(f"reservation {rt.name!r}", length)
    for sg in tmpl.scaling_groups:
        for rt in sg.reservations:
            # <pcs>-<r>-<sg>[-<j>]-<rt>-rsv
            length = (pcs_len + 1 + r_digits + 1 + len(sg.name) + 1
                      + len(rt.name) + 4)
            if rt.scope == ReservationScope.PER_REPLICA:
                length += 1 + _digits(_sg_max_replicas(sg) - 1)
            check(f"scaling group {sg.name!r} reservation {rt.name!r}",
                  length)


_MAX_CHIPS_PER_HOST = max(g.chips_per_host for g in TPU_GENERATIONS.values())
_MAX_SLICE_CHIPS = max(g.max_slice_chips for g in TPU_GENERATIONS.values())


def _validate_chips(pcs: PodCliqueSet, errs: list[str],
                    levels: list[str] | None = None) -> None:
    """Chip requests must be physically realisable (topology/tpu.py):
    a pod lands on ONE host, so per-pod chips cannot exceed any
    generation's chips-per-host and must be a power of two (sub-host
    granularity is 1/2/4); a slice-packed gang cannot need more chips
    than the largest slice any generation builds. ``levels`` is the
    ACTIVE hierarchy (custom ClusterTopology) — the slice-budget rule
    applies whenever that hierarchy has a level named 'slice' (same
    physical meaning: one ICI mesh), at its position in THAT ordering;
    hierarchies without a slice level skip the budget (their domains'
    physics are unknown).
    """
    tmpl = pcs.spec.template
    lv = levels if levels else _LEVELS
    per_gen = ", ".join(f"{g.name}={g.chips_per_host}/host"
                        for g in TPU_GENERATIONS.values())
    for t in tmpl.cliques:
        n = t.tpu_chips_per_pod
        if n <= 0:
            continue
        f = f"clique {t.name!r}"
        if n > _MAX_CHIPS_PER_HOST:
            errs.append(
                f"{f}: tpu_chips_per_pod={n} exceeds every TPU "
                f"generation's host ({per_gen}); multi-host groups are "
                "expressed as replicas (one pod per host), not bigger pods")
        elif n & (n - 1):
            errs.append(f"{f}: tpu_chips_per_pod={n} is not a power of two "
                        "(host chip partitions are 1/2/4)")

    def gang_chips(cliques, replicas_of) -> int:
        return sum(t.tpu_chips_per_pod * replicas_of(t)
                   for t in cliques if t.tpu_chips_per_pod > 0)

    by_name = {t.name: t for t in tmpl.cliques}
    in_group = {name for sg in tmpl.scaling_groups for name in sg.clique_names}

    def packed_to_slice(topo: TopologyConstraint | None) -> bool:
        eff = topo or tmpl.topology
        # Unknown levels are reported by _validate_topology; here they
        # just mean "cannot assess the slice budget" — don't crash on
        # the same typo twice.
        return bool(eff and eff.required
                    and "slice" in lv
                    and eff.pack_level in lv
                    and lv.index(eff.pack_level) >= lv.index("slice"))

    standalone = [t for t in tmpl.cliques if t.name not in in_group]
    for t in standalone:
        if packed_to_slice(t.topology):
            total = t.tpu_chips_per_pod * _clique_max_replicas(t)
            if total > _MAX_SLICE_CHIPS:
                errs.append(
                    f"clique {t.name!r}: slice-packed gang needs {total} "
                    f"chips; no TPU generation builds a slice that large "
                    f"(max {_MAX_SLICE_CHIPS})")
    for sg in tmpl.scaling_groups:
        members = [by_name[m] for m in sg.clique_names if m in by_name]
        if packed_to_slice(sg.topology):
            total = gang_chips(members, _clique_max_replicas)
            if total > _MAX_SLICE_CHIPS:
                errs.append(
                    f"scaling group {sg.name!r}: one slice-packed replica "
                    f"needs {total} chips; no TPU generation builds a "
                    f"slice that large (max {_MAX_SLICE_CHIPS})")


def _validate_fleet_fit(pcs: PodCliqueSet, errs: list[str],
                        nodes: list | None) -> None:
    """Per-pod requests vs the LIVE fleet's host shapes (reference
    webhook validation checks pod resource requests against what nodes
    can serve; _validate_chips above only checks physical possibility
    across ALL TPU generations). A pod asking for more chips than any
    host in this fleet has can never schedule — growth doesn't fix it,
    because new slices of the fleet's generation have the same host
    shape. GANG-level fit is deliberately NOT checked here: a gang
    bigger than today's largest slice stays Pending and schedules when
    a bigger slice joins (the scheduler's optimism; proven by
    test_gang_does_not_fit_stays_pending). Skipped when the fleet is
    empty."""
    if not nodes:
        return
    max_host = max(n.spec.tpu_chips for n in nodes)
    for t in pcs.spec.template.cliques:
        n_chips = t.tpu_chips_per_pod
        if 0 < max_host < n_chips:
            errs.append(
                f"clique {t.name!r}: tpu_chips_per_pod={n_chips} but the "
                f"largest host in the live fleet has {max_host} chips; "
                "no node can serve this pod")


def _check_reservation_template(rt, f: str, seen: set[str],
                                errs: list[str]) -> None:
    """Shape rules shared by PCS-level and PCSG-level templates."""
    if not _NAME_RE.match(rt.name or ""):
        errs.append(f"{f}: invalid name (DNS-label-like, <= 52 chars)")
    if rt.name in seen:
        errs.append(f"duplicate reservation template name {rt.name!r}")
    seen.add(rt.name)
    if not isinstance(rt.scope, ReservationScope):
        errs.append(f"{f}: scope must be one of "
                    f"{[s.value for s in ReservationScope]}")
    if rt.slice_count < 1:
        errs.append(f"{f}: slice_count must be >= 1, got {rt.slice_count}")
    if rt.generation and rt.generation not in TPU_GENERATIONS:
        errs.append(f"{f}: unknown generation {rt.generation!r} "
                    f"(known: {sorted(TPU_GENERATIONS)})")
    if rt.topology and not re.fullmatch(r"\d+x\d+(x\d+)?", rt.topology):
        errs.append(f"{f}: topology {rt.topology!r} is not an ICI mesh "
                    "shape like '4x4' or '4x4x4'")


def _validate_reservations(pcs: PodCliqueSet, errs: list[str]) -> None:
    """Reservation templates at both levels (api/reservation.py;
    reference resource-sharing validation, proposal 390): unique DNS
    names, known slice shapes, existing clique filters, and
    non-overlapping coverage — a clique served by two reservations (at
    any level) would have no well-defined placement fence."""
    tmpl = pcs.spec.template
    sg_reservations = [(sg, rt) for sg in tmpl.scaling_groups
                       for rt in sg.reservations]
    if not tmpl.reservations and not sg_reservations:
        return
    clique_names = {t.name for t in tmpl.cliques}
    # Template names are unique PER SCOPE (two groups may both call
    # their reservation 'own' — composed object names cannot collide
    # since group names are unique; claim() below guards the rest).
    seen_by_scope: dict[str, set[str]] = {}
    covered: dict[str, str] = {}   # clique -> covering template name

    # PCSG-level first: nearest scope wins, so its coverage is claimed
    # before PCS-level templates are checked against it.
    for sg, rt in sg_reservations:
        f = f"scaling group {sg.name!r} reservation {rt.name!r}"
        _check_reservation_template(
            rt, f, seen_by_scope.setdefault(sg.name, set()), errs)
        members = set(sg.clique_names)
        for cn in rt.clique_names:
            if cn not in members:
                errs.append(f"{f}: clique_names entry {cn!r} is not a "
                            f"member of the group (members: "
                            f"{sorted(members)})")
        for cn in (rt.clique_names or sorted(members)):
            if cn in covered and cn in clique_names:
                errs.append(f"{f}: clique {cn!r} already covered by "
                            f"reservation {covered[cn]!r} (coverage must "
                            "not overlap)")
            covered.setdefault(cn, rt.name)

    for rt in tmpl.reservations:
        f = f"reservation {rt.name!r}"
        _check_reservation_template(
            rt, f, seen_by_scope.setdefault("", set()), errs)
        targets = rt.clique_names or sorted(clique_names)
        for cn in rt.clique_names:
            if cn not in clique_names:
                errs.append(f"{f}: clique_names entry {cn!r} matches no "
                            f"clique (have {sorted(clique_names)})")
        for cn in targets:
            if cn in covered and cn in clique_names:
                errs.append(
                    f"{f}: clique {cn!r} already covered by reservation "
                    f"{covered[cn]!r} (coverage must not overlap; a "
                    "cover-all PCS-level template needs a clique_names "
                    "filter when group-level reservations exist)")
            covered.setdefault(cn, rt.name)

    # Generated OBJECT names must be unique across templates x replicas:
    # AllReplicas '1-x' and PerReplica 'x' at replica 1 both compose to
    # '<pcs>-1-x-rsv' — two templates silently sharing one reservation.
    generated: dict[str, str] = {}
    from grove_tpu.api import namegen

    def claim(gn: str, owner: str) -> None:
        if gn in generated and generated[gn] != owner:
            errs.append(
                f"reservation {owner!r} generates object name {gn!r} "
                f"which collides with reservation {generated[gn]!r}; "
                "rename one template")
        generated.setdefault(gn, owner)

    # Worst-case replica range includes the autoscaling ceiling — the
    # collision must be caught at create, not at the first scale-out.
    max_r = pcs.spec.replicas
    if pcs.spec.auto_scaling is not None:
        max_r = max(max_r, pcs.spec.auto_scaling.max_replicas)
    for rt in tmpl.reservations:
        if rt.scope == ReservationScope.PER_REPLICA:
            for r in range(max(1, max_r)):
                claim(namegen.reservation_name(pcs.meta.name, rt.name, r),
                      rt.name)
        else:
            claim(namegen.reservation_name(pcs.meta.name, rt.name), rt.name)
    for sg, rt in sg_reservations:
        owner = f"{sg.name}/{rt.name}"
        for r in range(max(1, max_r)):
            if rt.scope == ReservationScope.PER_REPLICA:
                for j in range(max(1, _sg_max_replicas(sg))):
                    claim(namegen.pcsg_reservation_name(
                        pcs.meta.name, r, sg.name, rt.name, j), owner)
            else:
                claim(namegen.pcsg_reservation_name(
                    pcs.meta.name, r, sg.name, rt.name), owner)


# ---- update immutability table (reference podcliqueset.go:662-698) ----
# Explicit per-field rules: (human path, getter). Structure fields whose
# change cannot be reconciled by either rolling-update mode.

_IMMUTABLE_TEMPLATE_FIELDS = [
    ("spec.template.startup_type", lambda t: t.startup_type),
    ("spec.template.headless_service",
     lambda t: (t.headless_service.publish_not_ready_addresses
                if t.headless_service else None)),
    ("spec.template.scheduler_name", lambda t: t.scheduler_name),
    ("spec.template.topology",
     lambda t: (t.topology.pack_level, t.topology.required,
                t.topology.spread_level) if t.topology else None),
    # Resource sharing is immutable in the reference (proposal 390
    # "Immutability of Resource Sharing Fields"): re-scoping a live
    # reservation would strand placed gangs outside their fence.
    ("spec.template.reservations",
     lambda t: tuple((rt.name, rt.scope, rt.generation, rt.topology,
                      rt.slice_count, tuple(rt.clique_names))
                     for rt in t.reservations)),
]

# tpu_chips_per_pod is deliberately MUTABLE: a chip-count change is a
# structural update the replica-recreation rollout reconciles (gangs are
# re-planned); forbidding it would force delete-and-recreate for a
# resource resize.
_IMMUTABLE_CLIQUE_FIELDS = [
    ("starts_after", lambda t: tuple(t.starts_after)),
    ("topology", lambda t: (t.topology.pack_level, t.topology.required,
                            t.topology.spread_level) if t.topology else None),
]

_IMMUTABLE_SG_FIELDS = [
    ("clique_names", lambda sg: tuple(sg.clique_names)),
    ("min_available", lambda sg: sg.min_available),
    ("topology", lambda sg: (sg.topology.pack_level, sg.topology.required,
                             sg.topology.spread_level) if sg.topology else None),
    ("reservations",
     lambda sg: tuple((rt.name, rt.scope, rt.generation, rt.topology,
                       rt.slice_count, tuple(rt.clique_names))
                      for rt in sg.reservations)),
]


def _validate_update(pcs: PodCliqueSet, old: PodCliqueSet,
                     errs: list[str]) -> None:
    tmpl, old_tmpl = pcs.spec.template, old.spec.template
    names = [t.name for t in tmpl.cliques]
    if [t.name for t in old_tmpl.cliques] != names:
        errs.append("clique set is immutable (got a different clique "
                    "name list); create a new PodCliqueSet instead")
    for path, get in _IMMUTABLE_TEMPLATE_FIELDS:
        if get(old_tmpl) != get(tmpl):
            if path.endswith("startup_type"):
                # Both sides have been through defaulting, so a mismatch
                # can come from inference (startup_type left unset, edges
                # added or removed) — say so instead of blaming a field
                # the user never touched.
                msg = (f"startup_type is immutable (stored "
                       f"{get(old_tmpl).value if get(old_tmpl) else None}, "
                       f"update resolves to "
                       f"{get(tmpl).value if get(tmpl) else None})")
                if tmpl.startup_type is StartupType.EXPLICIT:
                    msg += ("; adding starts_after edges infers "
                            "CliqueStartupTypeExplicit — set startup_type "
                            "explicitly on create to use edges later")
                errs.append(msg)
            else:
                errs.append(f"{path} is immutable "
                            f"(was {get(old_tmpl)!r}, got {get(tmpl)!r})")
    old_cliques = {t.name: t for t in old_tmpl.cliques}
    for t in tmpl.cliques:
        o = old_cliques.get(t.name)
        if o is None:
            continue
        for path, get in _IMMUTABLE_CLIQUE_FIELDS:
            if get(o) != get(t):
                errs.append(f"clique {t.name!r}: {path} is immutable "
                            f"(was {get(o)!r}, got {get(t)!r})")
    old_sgs = {sg.name: sg for sg in old_tmpl.scaling_groups}
    if set(old_sgs) != {sg.name for sg in tmpl.scaling_groups}:
        errs.append("scaling group set is immutable (names changed)")
    for sg in tmpl.scaling_groups:
        o = old_sgs.get(sg.name)
        if o is None:
            continue
        for path, get in _IMMUTABLE_SG_FIELDS:
            if get(o) != get(sg):
                errs.append(f"scaling group {sg.name!r}: {path} is "
                            f"immutable (was {get(o)!r}, got {get(sg)!r})")


def validate_podcliqueset(pcs: PodCliqueSet,
                          registry: Registry | None = None,
                          old: PodCliqueSet | None = None,
                          nodes: list | None = None,
                          topology_levels: list[str] | None = None
                          ) -> list[str]:
    """Return all problems (empty == admitted). ``nodes`` (the live
    fleet, supplied by the admission chain) enables the
    requests-vs-host-shapes rules; ``topology_levels`` (the active
    ClusterTopology's outer→inner domains, also chain-supplied) makes
    constraint resolution validate against the hierarchy the scheduler
    actually uses. None falls back to the built-in TPU levels."""
    errs = _validate_shape(pcs)
    if errs:
        return errs
    if not _NAME_RE.match(pcs.meta.name):
        errs.append(f"metadata.name {pcs.meta.name!r} must be DNS-label-like "
                    "(lowercase alphanumerics and '-', <= 52 chars)")
    spec = pcs.spec
    tmpl = spec.template
    # Old-object lookups for ratcheted rules (see _ratchet).
    _old_cliques = {t.name: t for t in
                    old.spec.template.cliques} if old else {}
    _old_sgs = {sg.name: sg for sg in
                old.spec.template.scaling_groups} if old else {}
    if spec.replicas < 1:
        errs.append(f"spec.replicas must be >= 1, got {spec.replicas}")
    if spec.auto_scaling is not None:
        _validate_autoscaling(
            "spec", spec.auto_scaling, spec.replicas, None, errs,
            enforce_ceiling=_ratchet(old.spec if old else None, spec,
                                     _scaling_shape))
    if not tmpl.cliques:
        errs.append("spec.template.cliques must not be empty")

    names = [t.name for t in tmpl.cliques]
    if len(set(names)) != len(names):
        errs.append(f"clique names must be unique: {names}")
    for t in tmpl.cliques:
        f = f"clique {t.name!r}"
        if not _NAME_RE.match(t.name or ""):
            errs.append(f"{f}: invalid name")
        if t.replicas < 1:
            errs.append(f"{f}: replicas must be >= 1")
        if t.min_available is not None and not (
                1 <= t.min_available <= t.replicas):
            errs.append(f"{f}: min_available {t.min_available} outside "
                        f"[1, {t.replicas}]")
        if t.tpu_chips_per_pod < 0:
            errs.append(f"{f}: tpu_chips_per_pod must be >= 0")
        if t.priority_class and not _NAME_RE.match(t.priority_class):
            errs.append(f"{f}: invalid priority_class name "
                        f"{t.priority_class!r}")
        _validate_container(f + ".container", t.container, errs)
        if t.auto_scaling is not None:
            _validate_autoscaling(
                f, t.auto_scaling, t.replicas, t.min_available, errs,
                enforce_ceiling=_ratchet(_old_cliques.get(t.name), t,
                                         _scaling_shape))
        _validate_topology(f + ".topology", t.topology, tmpl.topology,
                           errs, levels=topology_levels,
                           resolve=old is None)

    # startup DAG (reference podcliquedeps.go:53: Tarjan SCC)
    # Declared edges under IN_ORDER/ANY_ORDER would be silently ignored —
    # reject the contradiction instead.
    if tmpl.startup_type is not None and tmpl.startup_type != StartupType.EXPLICIT:
        for t in tmpl.cliques:
            if t.starts_after:
                errs.append(
                    f"clique {t.name!r}: starts_after requires startup_type "
                    f"{StartupType.EXPLICIT.value}, got "
                    f"{tmpl.startup_type.value}")
    known = set(names)
    graph = {t.name: [] for t in tmpl.cliques}
    for t in tmpl.cliques:
        # Ratcheted (starts_after is immutable on update, so without
        # ratcheting a pre-existing duplicate would brick the object).
        edges_enforced = _ratchet(_old_cliques.get(t.name), t,
                                  lambda x: tuple(x.starts_after))
        if edges_enforced and \
                len(set(t.starts_after)) != len(t.starts_after):
            # reference sliceMustHaveUniqueElements
            # (validation/podcliqueset.go:549)
            errs.append(f"clique {t.name!r}: starts_after has duplicate "
                        f"entries: {t.starts_after}")
        for dep in t.starts_after:
            if not dep:
                if edges_enforced:
                    errs.append(f"clique {t.name!r}: starts_after entry "
                                "is empty")
            elif dep == t.name:
                errs.append(f"clique {t.name!r}: starts_after itself")
            elif dep not in known:
                errs.append(f"clique {t.name!r}: starts_after unknown clique "
                            f"{dep!r}")
            else:
                graph[t.name].append(dep)
    for scc in tarjan_sccs(graph):
        if len(scc) > 1:
            errs.append(f"starts_after cycle detected: {sorted(scc)}")

    # scaling groups
    clique_by_name = {t.name: t for t in tmpl.cliques}
    sg_names = [sg.name for sg in tmpl.scaling_groups]
    if len(set(sg_names)) != len(sg_names):
        errs.append(f"scaling group names must be unique: {sg_names}")
    seen_members: dict[str, str] = {}
    for sg in tmpl.scaling_groups:
        f = f"scaling group {sg.name!r}"
        if not _NAME_RE.match(sg.name or ""):
            errs.append(f"{f}: invalid name")
        if sg.name in known:
            # Generated names interleave <clique> and <sg> segments at
            # the same position; one string naming both makes child
            # names (and debugging) ambiguous.
            errs.append(f"{f}: name collides with a clique name")
        if not sg.clique_names:
            errs.append(f"{f}: clique_names must not be empty")
        if sg.replicas < 1:
            errs.append(f"{f}: replicas must be >= 1")
        if sg.min_available is not None and not (
                1 <= sg.min_available <= sg.replicas):
            errs.append(f"{f}: min_available {sg.min_available} outside "
                        f"[1, {sg.replicas}]")
        for m in sg.clique_names:
            if m not in known:
                errs.append(f"{f}: references unknown clique {m!r}")
            elif m in seen_members:
                errs.append(f"{f}: clique {m!r} already in scaling group "
                            f"{seen_members[m]!r}")
            else:
                seen_members[m] = sg.name
                # Members scale with the group — a per-member autoscaler
                # would fight the PCSG one over the same replica field.
                if clique_by_name[m].auto_scaling is not None:
                    errs.append(
                        f"{f}: member clique {m!r} declares its own "
                        "auto_scaling; scaling-group members scale only "
                        "through the group's auto_scaling")
        if sg.auto_scaling is not None:
            _validate_autoscaling(
                f, sg.auto_scaling, sg.replicas, sg.min_available, errs,
                enforce_ceiling=_ratchet(_old_sgs.get(sg.name), sg,
                                         _scaling_shape))
        _validate_topology(f + ".topology", sg.topology, tmpl.topology,
                           errs, levels=topology_levels,
                           resolve=old is None)

    _validate_topology("spec.template.topology", tmpl.topology, None,
                       errs, levels=topology_levels,
                       resolve=old is None)
    if tmpl.termination_delay_seconds is not None \
            and tmpl.termination_delay_seconds < 0:
        errs.append("termination_delay_seconds must be >= 0")
    if not (-1_000_000 <= tmpl.priority <= 1_000_000):
        errs.append(f"spec.template.priority {tmpl.priority} outside "
                    "[-1000000, 1000000]")
    if tmpl.priority_class and not _NAME_RE.match(tmpl.priority_class):
        errs.append(f"invalid priority_class name {tmpl.priority_class!r}")

    _validate_name_budgets(pcs, errs)
    _validate_chips(pcs, errs, levels=topology_levels)
    if old is None:
        # Live-fleet fit gates CREATION only: a fleet that shrinks
        # under a running PCS must not brick every subsequent spec
        # update (autoscaler replica writes included) of an object
        # that was admissible when created.
        _validate_fleet_fit(pcs, errs, nodes)
    _validate_reservations(pcs, errs)

    # update immutability (reference validation: structure is immutable,
    # content rolls)
    if old is not None:
        _validate_update(pcs, old, errs)

    # scheduler-specific validation (reference backend.ValidatePodCliqueSet)
    if registry is not None:
        try:
            backend = registry.get(tmpl.scheduler_name or None)
            errs.extend(backend.validate_pcs(pcs))
        except KeyError:
            errs.append(f"unknown scheduler profile "
                        f"{tmpl.scheduler_name!r}; have {registry.profiles()}")
    return errs


# Label keys are ``[prefix/]name``: prefix a DNS subdomain (<= 253),
# name alphanumeric with -_. inside (<= 63) — the k8s label-key rules
# (reference admission/clustertopology/validation enforces qualified
# names on topology keys the same way).
_LABEL_NAME_RE = re.compile(r"^[A-Za-z0-9]([A-Za-z0-9._-]{0,61}[A-Za-z0-9])?$")
_DNS_SUBDOMAIN_RE = re.compile(
    r"^[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?(\.[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?)*$")


def _label_key_problems(key: str) -> str | None:
    if len(key) > 317:                      # 253 prefix + '/' + 63 name
        return "too long"
    prefix, sep, name = key.rpartition("/")
    if sep and (len(prefix) > 253 or not _DNS_SUBDOMAIN_RE.match(prefix)):
        return f"prefix {prefix!r} is not a DNS subdomain"
    if len(name) > 63 or not _LABEL_NAME_RE.match(name):
        return f"name {name!r} is not a qualified label name"
    return None


def validate_clustertopology(ct: ClusterTopology) -> list[str]:
    """W5: level uniqueness, domain naming, node-label key syntax."""
    errs: list[str] = []
    domains = [lvl.domain for lvl in ct.spec.levels]
    labels = [lvl.node_label for lvl in ct.spec.levels]
    if not domains:
        errs.append("spec.levels must not be empty")
    if len(set(domains)) != len(domains):
        errs.append(f"duplicate level domains: {domains}")
    if len(set(labels)) != len(labels):
        errs.append(f"duplicate level node_labels: {labels}")
    for lvl in ct.spec.levels:
        if not lvl.domain or not lvl.node_label:
            errs.append(f"level {lvl}: domain and node_label are required")
            continue
        if not _NAME_RE.match(lvl.domain):
            errs.append(f"level domain {lvl.domain!r} must be "
                        "DNS-label-like (constraints reference it)")
        problem = _label_key_problems(lvl.node_label)
        if problem:
            errs.append(f"level {lvl.domain!r}: node_label "
                        f"{lvl.node_label!r}: {problem}")
    return errs
