"""Validation admission — the PodCliqueSet rule set.

Role parity with reference admission/pcs/validation/ (6,289 LoC across 13
files), the rules that shape every downstream object:

- structural: names, replica/min_available bounds, uniqueness
- startup DAG: StartsAfter references exist and form a DAG (cycle
  detection via Tarjan SCC, reference podcliquedeps.go:53)
- topology: levels must exist in the hierarchy; child constraints must be
  at least as strict as the parent's (reference topologyconstraints.go)
- scaling groups: member cliques exist, belong to exactly one group
- update immutability: startup type, clique set, scaling-group membership
- scheduler-specific checks via Backend.validate_pcs
"""

from __future__ import annotations

import re

from grove_tpu.api.clustertopology import ClusterTopology, DEFAULT_TPU_LEVELS
from grove_tpu.api.podcliqueset import (PodCliqueSet, StartupType,
                                        TopologyConstraint)
from grove_tpu.scheduler.framework import Registry

_NAME_RE = re.compile(r"^[a-z0-9]([a-z0-9-]{0,50}[a-z0-9])?$")

_LEVELS = [lvl.domain for lvl in DEFAULT_TPU_LEVELS]  # outer -> inner


def _level_index(level: str) -> int:
    return _LEVELS.index(level)


def tarjan_sccs(graph: dict[str, list[str]]) -> list[list[str]]:
    """Tarjan strongly-connected components (iterative)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(root: str) -> None:
        work = [(root, iter(graph.get(root, ())))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in graph:
                    continue
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(graph.get(w, ()))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                sccs.append(scc)

    for node in graph:
        if node not in index:
            strongconnect(node)
    return sccs


def _validate_topology(field: str, topo: TopologyConstraint | None,
                       parent: TopologyConstraint | None,
                       errs: list[str]) -> None:
    if topo is None:
        return
    if topo.pack_level and topo.pack_level not in _LEVELS:
        errs.append(f"{field}.pack_level: unknown level {topo.pack_level!r}; "
                    f"levels: {_LEVELS}")
    if topo.spread_level and topo.spread_level not in _LEVELS:
        errs.append(f"{field}.spread_level: unknown level "
                    f"{topo.spread_level!r}; levels: {_LEVELS}")
    if (parent is not None and parent.pack_level and topo.pack_level
            and _level_index(topo.pack_level) < _level_index(parent.pack_level)):
        # child packs at an outer (looser) level than the parent demands
        errs.append(
            f"{field}.pack_level {topo.pack_level!r} is looser than the "
            f"template constraint {parent.pack_level!r} (child must be at "
            "least as strict)")


def validate_podcliqueset(pcs: PodCliqueSet,
                          registry: Registry | None = None,
                          old: PodCliqueSet | None = None) -> list[str]:
    """Return all problems (empty == admitted)."""
    errs: list[str] = []
    if not _NAME_RE.match(pcs.meta.name):
        errs.append(f"metadata.name {pcs.meta.name!r} must be DNS-label-like "
                    "(lowercase alphanumerics and '-', <= 52 chars)")
    spec = pcs.spec
    tmpl = spec.template
    if spec.replicas < 1:
        errs.append(f"spec.replicas must be >= 1, got {spec.replicas}")
    if spec.auto_scaling is not None:
        a = spec.auto_scaling
        if a.min_replicas > a.max_replicas:
            errs.append(f"spec.auto_scaling min {a.min_replicas} > max "
                        f"{a.max_replicas}")
        if a.min_replicas < 1:
            errs.append("spec.auto_scaling.min_replicas must be >= 1")
    if not tmpl.cliques:
        errs.append("spec.template.cliques must not be empty")

    names = [t.name for t in tmpl.cliques]
    if len(set(names)) != len(names):
        errs.append(f"clique names must be unique: {names}")
    for t in tmpl.cliques:
        f = f"clique {t.name!r}"
        if not _NAME_RE.match(t.name or ""):
            errs.append(f"{f}: invalid name")
        if t.replicas < 1:
            errs.append(f"{f}: replicas must be >= 1")
        if t.min_available is not None and not (
                1 <= t.min_available <= t.replicas):
            errs.append(f"{f}: min_available {t.min_available} outside "
                        f"[1, {t.replicas}]")
        if t.tpu_chips_per_pod < 0:
            errs.append(f"{f}: tpu_chips_per_pod must be >= 0")
        if t.auto_scaling is not None:
            a = t.auto_scaling
            if a.min_replicas < 1:
                errs.append(f"{f}: auto_scaling.min_replicas must be >= 1")
            if a.min_replicas > a.max_replicas:
                errs.append(f"{f}: auto_scaling min {a.min_replicas} > max "
                            f"{a.max_replicas}")
            if t.min_available is not None and a.min_replicas < t.min_available:
                errs.append(f"{f}: auto_scaling.min_replicas must be >= "
                            f"min_available (the gang floor)")
        _validate_topology(f + ".topology", t.topology, tmpl.topology, errs)

    # startup DAG (reference podcliquedeps.go:53: Tarjan SCC)
    # Declared edges under IN_ORDER/ANY_ORDER would be silently ignored —
    # reject the contradiction instead.
    if tmpl.startup_type is not None and tmpl.startup_type != StartupType.EXPLICIT:
        for t in tmpl.cliques:
            if t.starts_after:
                errs.append(
                    f"clique {t.name!r}: starts_after requires startup_type "
                    f"{StartupType.EXPLICIT.value}, got "
                    f"{tmpl.startup_type.value}")
    known = set(names)
    graph = {t.name: [] for t in tmpl.cliques}
    for t in tmpl.cliques:
        for dep in t.starts_after:
            if dep == t.name:
                errs.append(f"clique {t.name!r}: starts_after itself")
            elif dep not in known:
                errs.append(f"clique {t.name!r}: starts_after unknown clique "
                            f"{dep!r}")
            else:
                graph[t.name].append(dep)
    for scc in tarjan_sccs(graph):
        if len(scc) > 1:
            errs.append(f"starts_after cycle detected: {sorted(scc)}")

    # scaling groups
    sg_names = [sg.name for sg in tmpl.scaling_groups]
    if len(set(sg_names)) != len(sg_names):
        errs.append(f"scaling group names must be unique: {sg_names}")
    seen_members: dict[str, str] = {}
    for sg in tmpl.scaling_groups:
        f = f"scaling group {sg.name!r}"
        if not _NAME_RE.match(sg.name or ""):
            errs.append(f"{f}: invalid name")
        if not sg.clique_names:
            errs.append(f"{f}: clique_names must not be empty")
        if sg.replicas < 1:
            errs.append(f"{f}: replicas must be >= 1")
        if sg.min_available is not None and not (
                1 <= sg.min_available <= sg.replicas):
            errs.append(f"{f}: min_available {sg.min_available} outside "
                        f"[1, {sg.replicas}]")
        for m in sg.clique_names:
            if m not in known:
                errs.append(f"{f}: references unknown clique {m!r}")
            elif m in seen_members:
                errs.append(f"{f}: clique {m!r} already in scaling group "
                            f"{seen_members[m]!r}")
            else:
                seen_members[m] = sg.name
        if sg.auto_scaling is not None:
            a = sg.auto_scaling
            if a.min_replicas < 1:
                errs.append(f"{f}: auto_scaling.min_replicas must be >= 1")
            if a.min_replicas > a.max_replicas:
                errs.append(f"{f}: auto_scaling min {a.min_replicas} > max "
                            f"{a.max_replicas}")
            if sg.min_available is not None \
                    and a.min_replicas < sg.min_available:
                errs.append(f"{f}: auto_scaling.min_replicas must be >= "
                            "min_available (the gang floor)")
        _validate_topology(f + ".topology", sg.topology, tmpl.topology, errs)

    _validate_topology("spec.template.topology", tmpl.topology, None, errs)
    if tmpl.termination_delay_seconds is not None \
            and tmpl.termination_delay_seconds < 0:
        errs.append("termination_delay_seconds must be >= 0")

    # update immutability (reference validation: structure is immutable,
    # content rolls)
    if old is not None:
        old_tmpl = old.spec.template
        if [t.name for t in old_tmpl.cliques] != names:
            errs.append("clique set is immutable (got a different clique "
                        "name list); create a new PodCliqueSet instead")
        if old_tmpl.startup_type != tmpl.startup_type:
            # Both sides have been through defaulting, so a mismatch can
            # come from inference (startup_type left unset, edges added or
            # removed) — say so instead of blaming a field the user never
            # touched.
            msg = (f"startup_type is immutable (stored "
                   f"{old_tmpl.startup_type.value if old_tmpl.startup_type else None}, "
                   f"update resolves to "
                   f"{tmpl.startup_type.value if tmpl.startup_type else None})")
            if tmpl.startup_type is StartupType.EXPLICIT:
                msg += ("; adding starts_after edges infers "
                        "CliqueStartupTypeExplicit — set startup_type "
                        "explicitly on create to use edges later")
            errs.append(msg)
        old_sg = {sg.name: list(sg.clique_names)
                  for sg in old_tmpl.scaling_groups}
        new_sg = {sg.name: list(sg.clique_names)
                  for sg in tmpl.scaling_groups}
        if old_sg != new_sg:
            errs.append("scaling group membership is immutable")

    # scheduler-specific validation (reference backend.ValidatePodCliqueSet)
    if registry is not None:
        try:
            backend = registry.get(tmpl.scheduler_name or None)
            errs.extend(backend.validate_pcs(pcs))
        except KeyError:
            errs.append(f"unknown scheduler profile "
                        f"{tmpl.scheduler_name!r}; have {registry.profiles()}")
    return errs


def validate_clustertopology(ct: ClusterTopology) -> list[str]:
    """W5: level uniqueness + label rules."""
    errs: list[str] = []
    domains = [lvl.domain for lvl in ct.spec.levels]
    labels = [lvl.node_label for lvl in ct.spec.levels]
    if not domains:
        errs.append("spec.levels must not be empty")
    if len(set(domains)) != len(domains):
        errs.append(f"duplicate level domains: {domains}")
    if len(set(labels)) != len(labels):
        errs.append(f"duplicate level node_labels: {labels}")
    for lvl in ct.spec.levels:
        if not lvl.domain or not lvl.node_label:
            errs.append(f"level {lvl}: domain and node_label are required")
    return errs
