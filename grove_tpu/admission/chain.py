"""Admission chain — the in-process webhook pipeline.

The reference receives admission over HTTPS from the apiserver (L5);
this control plane owns its store, so admission installs as a write hook:
every create/update passes defaulting → validation → authorization before
commit. Same guarantees, no TLS plumbing (the cert-manager component C6
becomes moot by construction).
"""

from __future__ import annotations

from typing import Any

from grove_tpu.admission.authorization import authorize
from grove_tpu.admission.defaulting import default_podcliqueset
from grove_tpu.admission.validation import (
    validate_clustertopology,
    validate_podcliqueset,
)
from grove_tpu.api.config import OperatorConfiguration
from grove_tpu.runtime.errors import ForbiddenError, ValidationError
from grove_tpu.scheduler.framework import Registry


class AdmissionChain:
    def __init__(self, config: OperatorConfiguration,
                 registry: Registry | None = None,
                 store: Any = None):
        self.config = config
        self.registry = registry
        self._store = store    # fleet access for requests-vs-host rules

    def _fleet_nodes(self) -> list | None:
        """Live Nodes for the fleet-fit validation rules. The store's
        RLock makes the nested list safe from inside an admit call."""
        if self._store is None:
            return None
        from grove_tpu.api import Node
        try:
            return self._store.list(Node, namespace=None)
        except Exception:  # noqa: BLE001 — fleet rules are best-effort
            return None

    def _topology_levels(self) -> list | None:
        """The active ClusterTopology's outer→inner domain names, so
        constraint levels validate against the hierarchy the scheduler
        actually uses (reference validateResolvableTopologyConstraint).
        Selection is deterministic and matches the scheduler side: the
        CT named 'default' (what ensure_default_topology creates and
        the backends sync), else the single existing CT; with multiple
        non-default CTs the hierarchy is ambiguous → skip (fall back to
        built-in levels) rather than guess one the scheduler may not
        use."""
        if self._store is None:
            return None
        from grove_tpu.api import ClusterTopology
        try:
            cts = [ct for ct in self._store.list(ClusterTopology,
                                                 namespace=None)
                   if ct.spec.levels]
        except Exception:  # noqa: BLE001 — best-effort
            return None
        chosen = next((ct for ct in cts if ct.meta.name == "default"),
                      cts[0] if len(cts) == 1 else None)
        if chosen is None:
            return None
        return [lvl.domain for lvl in chosen.spec.levels]

    def admit(self, verb: str, obj: Any, old: Any, actor: str) -> Any:
        """Mutate (defaulting) and validate; raise on rejection."""
        denial = authorize(self.config.authorizer, actor, verb, obj)
        if denial:
            raise ForbiddenError(denial, operation=f"admission/{verb}")
        if verb not in ("create", "update"):
            return obj
        if obj.KIND == "PodCliqueSet":
            obj = default_podcliqueset(obj)
            # Fleet-fit rules gate creation only — don't pay an
            # O(fleet) Node list+clone on every spec update.
            # Live-cluster context (fleet shapes, CT levels) gates
            # CREATION only — ratcheting: a fleet/CT change under a
            # running PCS must not brick its spec updates.
            nodes = self._fleet_nodes() if old is None else None
            levels = self._topology_levels() if old is None else None
            problems = validate_podcliqueset(
                obj, self.registry, old, nodes=nodes,
                topology_levels=levels)
            if problems:
                raise ValidationError(
                    f"PodCliqueSet {obj.meta.name!r} rejected: "
                    + "; ".join(problems),
                    operation=f"admission/{verb}")
        elif obj.KIND == "ClusterTopology":
            problems = validate_clustertopology(obj)
            if problems:
                raise ValidationError(
                    f"ClusterTopology {obj.meta.name!r} rejected: "
                    + "; ".join(problems),
                    operation=f"admission/{verb}")
        return obj


def install_admission(store, config: OperatorConfiguration,
                      registry: Registry | None = None) -> AdmissionChain:
    chain = AdmissionChain(config, registry, store=store)
    store.set_admission(chain)
    return chain
