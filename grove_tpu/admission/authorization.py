"""Authorization admission — who may mutate grove-managed resources.

Role parity with reference admission/pcs/authorization/handler.go:40: when
enabled, only the operator service account (and configured exempt actors)
may mutate resources the operator manages (children carrying the
managed-by label); users manage the world through the PodCliqueSet spec,
never by poking its children.
"""

from __future__ import annotations

from grove_tpu.api import constants as c
from grove_tpu.api.config import AuthorizerConfig

OPERATOR_ACTOR = "system:grove-operator"
NODE_ACTOR = "system:node-agent"
SCHEDULER_ACTOR = "system:scheduler"

_SYSTEM_ACTORS = {OPERATOR_ACTOR, NODE_ACTOR, SCHEDULER_ACTOR}

# Kinds users declare themselves (never operator-managed at the top level)
_USER_KINDS = {"PodCliqueSet", "ClusterTopology", "Node"}


def authorize(config: AuthorizerConfig, actor: str, verb: str,
              obj) -> str | None:
    """Return a denial message, or None to admit."""
    if not config.enabled:
        return None
    if actor in _SYSTEM_ACTORS or actor in config.exempt_actors:
        return None
    if actor.startswith(c.WORKLOAD_ACTOR_PREFIX):
        # Workload identity tokens are metrics-push credentials, full
        # stop — a compromised pod must not be able to mutate ANY
        # object, including user kinds an anonymous caller could not
        # touch either (server.py also rejects these before admission;
        # this is the defense-in-depth layer).
        return (f"workload actor {actor!r} may not {verb} anything; "
                "workload tokens only authenticate metric pushes")
    if obj.KIND == "Secret":
        # Secrets are control-plane-minted only: letting users create
        # one lets them squat the deterministic workload-token name and
        # silently disable a PCS's workload identity.
        return (f"actor {actor!r} may not {verb} Secrets; they are "
                "minted by the control plane")
    if obj.KIND in _USER_KINDS:
        return None
    if obj.meta.labels.get(c.LABEL_MANAGED_BY) == c.LABEL_MANAGED_BY_VALUE:
        return (f"actor {actor!r} may not {verb} grove-managed "
                f"{obj.KIND} {obj.meta.name!r}; edit the owning "
                "PodCliqueSet instead")
    return None
