"""Composable fault injectors — every fault drives a PUBLIC surface.

The rule that keeps the harness honest: a fault may only do what the
real world can do to the control plane — write API objects (node
heartbeats going stale, node objects vanishing), create workloads
(preemption storms are just high-priority gangs), push metrics
(autoscale flapping is what a noisy engine fleet does), kill processes
(agents, the leader), or trip the sanctioned wire fault hook
(httpclient.arm_watch_gap — the injected form of a history-ring 410).
No store internals, no controller privates: if a fault needs a back
door, the production surface is what's missing.

Each fault is ``inject(ctx)`` / ``heal(ctx)``; both are safe to call
repeatedly (flapping = inject/heal in a loop). The scenario runner
composes them from a seeded RNG so every run is reproducible from its
seed (docs/design/chaos-harness.md).
"""

from __future__ import annotations

import random
import time
from typing import Any

from grove_tpu.api import Node, PodCliqueSet, constants as c, new_meta
from grove_tpu.api.core import ContainerSpec
from grove_tpu.api.podcliqueset import (
    PodCliqueSetSpec,
    PodCliqueSetTemplate,
    PodCliqueTemplate,
    TopologyConstraint,
)
from grove_tpu.runtime.errors import GroveError, NotFoundError
from grove_tpu.runtime.logger import get_logger


class ChaosContext:
    """Shared handles the faults act through: the cluster under test,
    the seeded RNG, and (when the runner wires them) the HTTP surface
    for wire-path faults. Faults must treat everything here as the
    outside world does — ``client`` is the API, ``http`` is the wire."""

    def __init__(self, cluster, rng: random.Random,
                 namespace: str = "default",
                 base_url: str = "", http: Any = None,
                 wire_informers: dict | None = None,
                 workload_pcs: str = "", workload_pcsg: str = "",
                 autoscale_metric: str = "queue_depth",
                 autoscale_target: float = 10.0):
        self.cluster = cluster
        self.client = cluster.client
        self.rng = rng
        self.namespace = namespace
        self.base_url = base_url
        self.http = http                      # HttpClient for wire faults
        self.wire_informers = wire_informers or {}
        self.workload_pcs = workload_pcs
        self.workload_pcsg = workload_pcsg    # autoscaled PCSG full name
        self.autoscale_metric = autoscale_metric
        self.autoscale_target = autoscale_target
        self.log = get_logger("chaos")

    # -- world helpers ----------------------------------------------------

    def nodes(self) -> list[Node]:
        return self.client.list(Node, self.namespace)

    def slices(self) -> list[str]:
        return sorted({n.meta.labels.get(c.NODE_LABEL_SLICE, "")
                       for n in self.nodes()} - {""})

    def nodes_of_slice(self, slice_name: str) -> list[Node]:
        return [n for n in self.nodes()
                if n.meta.labels.get(c.NODE_LABEL_SLICE) == slice_name]

    def find_kubelet(self):
        from grove_tpu.agent.node import FakeKubeletPool
        for r in self.cluster.manager.runnables:
            if isinstance(r, FakeKubeletPool):
                return r
        return None

    def push_metric(self, value: float, metric: str | None = None,
                    reporter: str = "chaos") -> bool:
        """Autoscaling signal through the wire surface the engines use
        (POST /metrics/push) — never the in-process registry. The POST
        is built directly (not via serving.metrics_push, which derives
        the reporter from GROVE_POD_NAME) because chaos needs DISTINCT
        reporters: the traffic pump and the flap fault must aggregate
        as two engines, not last-write-wins under one id."""
        if self.http is None or not self.workload_pcsg:
            return False
        try:
            self.http._request("POST", "/metrics/push", {
                "kind": "PodCliqueScalingGroup",
                "name": self.workload_pcsg,
                "namespace": self.namespace,
                "metric": metric or self.autoscale_metric,
                "value": value,
                "reporter": reporter,
            })
            return True
        except GroveError:
            return False   # advisory, like every metrics path


class Fault:
    """One injectable failure mode. ``inject`` breaks something through
    a public surface and returns truthy iff the fault actually FIRED
    (a no-op — no candidate node, no wire surface — returns False so
    the runner's fault-coverage accounting stays honest); ``heal``
    restores the precondition (the world healing — host repaired,
    traffic calming, process restarted). Both must tolerate being
    called when the fault is already (in)active."""

    name = "fault"

    def inject(self, ctx: ChaosContext) -> bool:
        raise NotImplementedError

    def heal(self, ctx: ChaosContext) -> None:
        raise NotImplementedError


class NodeHeartbeatLossFault(Fault):
    """A host's agent stops heartbeating (feeds
    controllers/nodelifecycle.py): the node is handed to the 'remote
    agent' world (spec.fake=False) with its last heartbeat already
    stale, so the node-lifecycle controller marks it NotReady and fails
    its pods for self-heal. Heal returns it to the fake-kubelet pool
    ready and heartbeat-exempt — the repaired-host analog. Calling
    inject/heal in a loop is heartbeat FLAPPING."""

    name = "node-heartbeat-loss"

    def __init__(self) -> None:
        self._lost: list[str] = []

    def inject(self, ctx: ChaosContext) -> bool:
        candidates = [n for n in ctx.nodes()
                      if n.spec.fake
                      and not n.meta.labels.get(c.LABEL_RESERVATION)]
        if not candidates:
            return False
        node = ctx.rng.choice(candidates)
        grace = ctx.cluster.manager.config.node_lifecycle.grace_seconds
        try:
            live = ctx.client.get(Node, node.meta.name, ctx.namespace)
            live.spec.fake = False
            live = ctx.client.update(live)
            # Recorded as soon as the FIRST write lands: if the status
            # write below conflicts, the node is already half-injected
            # (non-fake, no agent will ever heartbeat it) and heal()
            # must still restore it — otherwise the fleet silently
            # loses a node for the rest of the soak.
            self._lost.append(node.meta.name)
            live.status.heartbeat_time = time.time() - 2.0 * grace
            live.status.ready = True
            ctx.client.update_status(live)
        except (NotFoundError, GroveError) as e:
            ctx.log.warning("heartbeat-loss inject on %s failed: %s",
                            node.meta.name, e)
            return False
        ctx.log.info("chaos: node %s heartbeat gone stale", node.meta.name)
        return True

    def heal(self, ctx: ChaosContext) -> None:
        for name in self._lost:
            try:
                live = ctx.client.get(Node, name, ctx.namespace)
                live.spec.fake = True
                live = ctx.client.update(live)
                live.status.ready = True
                live.status.heartbeat_time = 0.0   # exempt again
                live.status.message = ""
                ctx.client.update_status(live)
            except (NotFoundError, GroveError):
                continue
        self._lost.clear()


class NodeDeleteFault(Fault):
    """A whole slice's node OBJECTS vanish (fleet shrink / hard host
    loss): the node-lifecycle orphan sweep fails their pods, gangs
    breach and self-heal elsewhere. Heal re-registers identical nodes
    (host repaired and re-joined)."""

    name = "node-delete"

    def __init__(self) -> None:
        # (name, generation, topology, slice, worker, pool)
        self._deleted: list[tuple[str, str, str, str, int, str]] = []

    def inject(self, ctx: ChaosContext) -> bool:
        slices = ctx.slices()
        if len(slices) < 2:
            return False  # never delete the last slice: nothing heals to
        victim = ctx.rng.choice(slices)
        for n in ctx.nodes_of_slice(victim):
            gen = n.meta.labels.get(
                c.NODE_LABEL_TPU_ACCELERATOR, "tpu-v5e").removeprefix("tpu-")
            self._deleted.append((
                n.meta.name, gen,
                n.meta.labels.get(c.NODE_LABEL_TPU_TOPOLOGY, "2x2"),
                victim, int(n.meta.labels.get(c.NODE_LABEL_SLICE_WORKER, 0)),
                n.meta.labels.get(c.NODE_LABEL_POOL, "pool-0")))
            try:
                ctx.client.delete(Node, n.meta.name, n.meta.namespace)
            except (NotFoundError, GroveError):
                continue
        ctx.log.info("chaos: slice %s nodes deleted", victim)
        return bool(self._deleted)

    def heal(self, ctx: ChaosContext) -> None:
        from grove_tpu.topology.fleet import build_node
        for _name, gen, topo, slice_name, worker, pool in self._deleted:
            fresh = build_node(gen, topo, slice_name, worker, pool=pool,
                               namespace=ctx.namespace)
            try:
                ctx.client.create(fresh)
            except GroveError:
                continue  # already re-registered
        self._deleted.clear()


class SpotReclaimFault(Fault):
    """A slice's spot capacity is reclaimed (GKE spot: the nodes vanish
    *together*, with advance notice): every node of a victim slice gets
    the ``ANNOTATION_RECLAIM_AT`` stamp through the public API — the
    node-lifecycle controller cordons them, the reclaim controller
    (grove_tpu/disruption) evacuates their gangs behind the checkpoint
    barrier. Heal is the reclamation actually happening followed by
    spot capacity returning: the noticed nodes are deleted and
    identical fresh ones re-register."""

    name = "spot-reclaim"

    def __init__(self, notice_window_s: float = 6.0) -> None:
        self.notice_window_s = notice_window_s
        # (name, generation, topology, slice, worker, pool)
        self._noticed: list[tuple[str, str, str, str, int, str]] = []

    def _notice_slice(self, ctx: ChaosContext, victim: str,
                      deadline: float) -> int:
        stamped = 0
        for n in ctx.nodes_of_slice(victim):
            gen = n.meta.labels.get(
                c.NODE_LABEL_TPU_ACCELERATOR, "tpu-v5e").removeprefix("tpu-")
            try:
                ctx.client.patch(Node, n.meta.name, {
                    "metadata": {"annotations": {
                        c.ANNOTATION_RECLAIM_AT: str(deadline)}}},
                    namespace=n.meta.namespace)
            except (NotFoundError, GroveError) as e:
                ctx.log.warning("reclaim notice on %s failed: %s",
                                n.meta.name, e)
                continue
            self._noticed.append((
                n.meta.name, gen,
                n.meta.labels.get(c.NODE_LABEL_TPU_TOPOLOGY, "2x2"),
                victim, int(n.meta.labels.get(c.NODE_LABEL_SLICE_WORKER, 0)),
                n.meta.labels.get(c.NODE_LABEL_POOL, "pool-0")))
            stamped += 1
        return stamped

    def inject(self, ctx: ChaosContext) -> bool:
        from grove_tpu.runtime.timescale import scaled
        slices = ctx.slices()
        if len(slices) < 2:
            return False  # a reclaim with no survivors is just node loss
        victim = ctx.rng.choice(slices)
        deadline = time.time() + scaled(self.notice_window_s)
        if not self._notice_slice(ctx, victim, deadline):
            return False
        ctx.log.info("chaos: slice %s spot-reclaim noticed "
                     "(withdraws in %.1fs)", victim,
                     deadline - time.time())
        return True

    def heal(self, ctx: ChaosContext) -> None:
        """The withdrawal, then the return: noticed nodes vanish (the
        reclamation really happens — mid-evacuation if the barrier or
        reland is still running, exactly the race the controller must
        survive), then identical fresh nodes re-register notice-free."""
        from grove_tpu.topology.fleet import build_node
        for name, *_ in self._noticed:
            try:
                ctx.client.delete(Node, name, ctx.namespace)
            except (NotFoundError, GroveError):
                continue
        for _name, gen, topo, slice_name, worker, pool in self._noticed:
            fresh = build_node(gen, topo, slice_name, worker, pool=pool,
                               namespace=ctx.namespace)
            try:
                ctx.client.create(fresh)
            except GroveError:
                continue  # already re-registered
        self._noticed.clear()


class DisruptionStormFault(Fault):
    """Overlapping planned disruptions — the coalescing stress: spot
    reclaim notices on MULTIPLE slices (staggered deadlines) while a
    rolling update churns the standing workload, so reclaim and
    rolling-update barriers land on the same gangs in the same window
    and the per-gang notice must coalesce instead of thrashing. Heal
    withdraws and re-registers the noticed capacity."""

    name = "disruption-storm"

    def __init__(self, notice_window_s: float = 6.0) -> None:
        self.notice_window_s = notice_window_s
        self._reclaim = SpotReclaimFault(notice_window_s)

    def inject(self, ctx: ChaosContext) -> bool:
        from grove_tpu.runtime.timescale import scaled
        slices = ctx.slices()
        if len(slices) < 3:
            return False  # storm needs >=2 victims and a survivor
        victims = ctx.rng.sample(slices, k=min(2, len(slices) - 1))
        fired = 0
        for i, victim in enumerate(victims):
            deadline = time.time() + scaled(
                self.notice_window_s + i * 0.5)
            fired += self._reclaim._notice_slice(ctx, victim, deadline)
        if not fired:
            return False
        self._roll_workload(ctx)
        ctx.log.info("chaos: disruption storm — %d slice(s) reclaim-"
                     "noticed + rolling update", len(victims))
        return True

    def _roll_workload(self, ctx: ChaosContext) -> None:
        """Template edit through the public API (the same surface a
        user deploy takes): a roll mid-reclaim makes both barrier
        callers coalesce on the standing gangs."""
        if not ctx.workload_pcs:
            return
        for _ in range(5):
            try:
                pcs = ctx.client.get(PodCliqueSet, ctx.workload_pcs,
                                     ctx.namespace)
                for t in pcs.spec.template.cliques:
                    t.container.env["CHAOS_DISRUPTION_STORM"] = str(
                        ctx.rng.randrange(1 << 30))
                ctx.client.update(pcs)
                return
            except NotFoundError:
                return
            except GroveError:
                time.sleep(0.05)   # conflict: re-read and retry

    def heal(self, ctx: ChaosContext) -> None:
        self._reclaim.heal(ctx)


class PreemptionStormFault(Fault):
    """A burst of high-priority single-slice gangs lands on a full
    fleet: the gang scheduler preempts the workload's elastic scaled
    gangs to make room (scheduler/backends._try_preempt_for). Heal
    deletes the storm; preempted capacity re-expands."""

    name = "preemption-storm"

    def __init__(self, burst: int = 2, pods: int = 2, priority: int = 100,
                 chips_per_pod: int = 4) -> None:
        """Each storm gang is ``pods`` x ``chips_per_pod`` chips
        slice-packed — sized so a burst fills the fleet's free
        headroom; composed with node loss it overflows into actual
        preemption of the workload's elastic scaled gangs."""
        self.burst = burst
        self.pods = pods
        self.priority = priority
        self.chips_per_pod = chips_per_pod
        self._names: list[str] = []

    def inject(self, ctx: ChaosContext) -> bool:
        for i in range(self.burst):
            name = f"storm-{ctx.rng.randrange(1 << 30):08x}-{i}"
            pcs = PodCliqueSet(
                meta=new_meta(name, namespace=ctx.namespace),
                spec=PodCliqueSetSpec(replicas=1, template=PodCliqueSetTemplate(
                    priority=self.priority,
                    topology=TopologyConstraint(pack_level="slice",
                                                required=True),
                    cliques=[PodCliqueTemplate(
                        name="burst", replicas=self.pods,
                        min_available=self.pods,
                        tpu_chips_per_pod=self.chips_per_pod,
                        container=ContainerSpec(argv=["sleep", "inf"]))])))
            try:
                ctx.client.create(pcs)
                self._names.append(name)
            except GroveError as e:
                ctx.log.warning("storm gang %s rejected: %s", name, e)
        ctx.log.info("chaos: preemption storm of %d high-priority gangs",
                     len(self._names))
        return bool(self._names)

    def heal(self, ctx: ChaosContext) -> None:
        for name in self._names:
            try:
                ctx.client.delete(PodCliqueSet, name, ctx.namespace)
            except (NotFoundError, GroveError):
                continue
        self._names.clear()


class WatchGapFault(Fault):
    """The wire watch's history-ring gap (410 Gone), injected through
    the sanctioned hook (httpclient.arm_watch_gap, env-gated on
    GROVE_FAULT_INJECT): every armed consumer must relist-and-resume
    (informer reseed) rather than die or serve a hole. The invariant
    checker then proves the wire caches reconverged with the store."""

    name = "watch-gap"

    def __init__(self, gaps: int = 1) -> None:
        self.gaps = gaps

    def inject(self, ctx: ChaosContext) -> bool:
        from grove_tpu.store.httpclient import arm_watch_gap
        if ctx.http is None:
            return False
        arm_watch_gap(ctx.http, self.gaps)
        ctx.log.info("chaos: armed %d watch gap(s)", self.gaps)
        return True

    def heal(self, ctx: ChaosContext) -> None:
        pass  # one-shot: consumed by the next watch poll(s)


class AutoscaleFlapFault(Fault):
    """A noisy engine fleet: the scaling signal spikes far above target
    (scale-out — new gangs) then collapses (scale-in after
    stabilization), pushed through POST /metrics/push exactly as
    serving engines report. Gang creates/destroys under churn are the
    point — the invariants must hold through both."""

    name = "autoscale-flap"

    def __init__(self, spike_factor: float = 3.0) -> None:
        self.spike_factor = spike_factor

    def inject(self, ctx: ChaosContext) -> bool:
        pushed = ctx.push_metric(ctx.autoscale_target * self.spike_factor)
        if pushed:
            ctx.log.info("chaos: autoscale signal spiked x%.1f",
                         self.spike_factor)
        return pushed

    def heal(self, ctx: ChaosContext) -> None:
        ctx.push_metric(ctx.autoscale_target * 0.1)


class AgentKillFault(Fault):
    """The node-agent process dies (kubelet crash): pods stop
    transitioning to Running/Ready until a replacement agent starts.
    Kill is ``stop()`` on the live FakeKubeletPool (exactly what
    process death does to its loops); heal starts a FRESH pool — an
    agent restart, not a resurrection."""

    name = "agent-kill"

    def __init__(self) -> None:
        self._killed = False

    def inject(self, ctx: ChaosContext) -> bool:
        pool = ctx.find_kubelet()
        if pool is None:
            return False
        pool.stop()
        ctx.cluster.manager.runnables.remove(pool)
        self._killed = True
        ctx.log.info("chaos: node agent killed")
        return True

    def heal(self, ctx: ChaosContext) -> None:
        if not self._killed:
            return
        from grove_tpu.agent.node import FakeKubeletPool
        fresh = FakeKubeletPool(ctx.cluster.manager.client)
        fresh.start()
        ctx.cluster.manager.runnables.append(fresh)
        self._killed = False
        ctx.log.info("chaos: node agent restarted")


class LeaderKillFault(Fault):
    """A leadership transition mid-chaos (grove_tpu/ha): a rival
    replica fences the store (epoch bump — exactly what a promoting
    standby does first) and this manager notices it lost, demoting:
    controllers park and DROP queued work, expectation stores clear,
    writer runnables pause. The fence is PROVEN on the spot — a write
    stamped with the deposed epoch must come back FencedError, else
    inject raises and the fault doesn't count toward coverage. Heal
    re-campaigns (promote: epoch bump past the rival, stamp, resync) —
    the soak's recovery waits then prove reconcile resumes cleanly,
    exercising transitions continuously as the ISSUE demands.

    Public-surface note: demote/promote are the manager's own
    leadership API (what the elector drives) and the epoch bump is the
    store's fencing verb — the same calls a real rival performs, like
    AgentKillFault killing kubelets through their pool."""

    name = "leader-kill"

    def __init__(self) -> None:
        self._deposed = False

    def inject(self, ctx: ChaosContext) -> bool:
        from grove_tpu.api import PodCliqueSet
        from grove_tpu.ha import ha_enabled
        from grove_tpu.runtime.errors import FencedError
        from grove_tpu.store.client import Client

        if not ha_enabled():
            # GROVE_HA=0 disables the fence on purpose: a transition
            # fault cannot prove (or exercise) anything — no-op, not
            # a false "guard is broken" failure.
            ctx.log.info("chaos: leader-kill skipped (GROVE_HA=0)")
            return False
        mgr = ctx.cluster.manager
        store = mgr.store
        rival_epoch = store.bump_epoch()        # the rival fences
        dropped = mgr.demote(leader_hint="chaos-rival")
        self._deposed = True
        # Prove the fence: a write carrying the PRE-rival epoch (what
        # this manager's in-flight reconciles still hold) must be
        # rejected at the store.
        probe = Client(store)
        probe.epoch = rival_epoch - 1
        try:
            probe.patch_status(PodCliqueSet, ctx.workload_pcs, {},
                               namespace=ctx.namespace)
        except FencedError:
            ctx.log.info("chaos: leadership lost at epoch %d (%d queued "
                         "items dropped); stale-epoch write fenced as "
                         "required", rival_epoch, dropped)
            return True
        except (NotFoundError, GroveError):
            pass
        raise AssertionError(
            "epoch fence did not fire: a stale-epoch write was accepted "
            "after the rival's bump — the zombie-leader guard is broken")

    def heal(self, ctx: ChaosContext) -> None:
        if not self._deposed:
            return
        epoch = ctx.cluster.manager.promote()   # re-campaign
        self._deposed = False
        ctx.log.info("chaos: re-promoted at epoch %d", epoch)


# name -> factory; the scenario runner samples these from its seed.
FAULT_REGISTRY: dict[str, type[Fault]] = {
    f.name: f for f in (NodeHeartbeatLossFault, NodeDeleteFault,
                        SpotReclaimFault, DisruptionStormFault,
                        PreemptionStormFault, WatchGapFault,
                        AutoscaleFlapFault, AgentKillFault,
                        LeaderKillFault)
}
