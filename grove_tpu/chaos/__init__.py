"""Chaos/soak harness — fault-injection scenarios with gang-invariant
checking (ROADMAP item 5; the reference's GS1-GS10 gang-correctness e2e
plus soak_test.go's repeated scale up/down, SURVEY.md §6).

Three layers (docs/design/chaos-harness.md):

- ``faults``      — composable injectors driven through public surfaces
- ``scenario``    — seeded runner composing fault schedules with
                    workload actions into named scenarios + a random mix
- ``invariants``  — the checker that sweeps the store and every debug
                    surface between cycles

``tools/chaos_soak.py`` fronts the harness; ``make chaos-smoke`` is the
CI gate, ``make chaos-soak`` the long run.
"""

from grove_tpu.chaos.faults import (  # noqa: F401
    FAULT_REGISTRY,
    ChaosContext,
    Fault,
)
from grove_tpu.chaos.invariants import (  # noqa: F401
    InvariantChecker,
    Violation,
)
from grove_tpu.chaos.scenario import (  # noqa: F401
    SCENARIOS,
    ScenarioRunner,
    run_leader_kill,
)
