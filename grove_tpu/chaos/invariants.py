"""Gang-invariant checker — what must stay true no matter the abuse.

Swept between chaos cycles (and usable standalone against any live
cluster), reading only the surfaces an operator has: the object store
through the client, the PR 3 trace milestones, the PR 5 explain
diagnosis, the PR 6 deploy observatory, and the rendered /metrics
text. Each invariant polls with a TIME_SCALE-scaled grace before
declaring a violation — the control plane is eventually consistent and
chaos leaves transients in flight; only a state that REFUSES to
converge is a bug.

The invariants (ISSUE 8 / reference GS1-GS10 analog):

- **gang-binding**     no gang partially bound beyond a deadline
                       (gang atomicity: all pods placed or none)
- **live-owner**       no object whose controller owner is gone
                       (cascade/expectations correctness)
- **pending-diagnosis** every pending gang carries a CURRENT
                       PlacementDiagnosis (explain never goes stale)
- **no-duplicates**    no duplicate pods per expectation key (the
                       SURVEY §7 double-create hazard's direct check)
- **gauge-consistency** grove_state_objects gauges match store counts
                       (the observability plane never lies)
- **wire-convergence** wire informer caches match the store after
                       gap injection (410 recovery is complete)
- **defrag-holds**     no dangling capacity hold: every defrag/roll
                       SliceReservation names a live gang that still
                       references it (leaked holds fence slices)
- **disruption-contract** every planned eviction honored the barrier:
                       an evicted gang's DisruptionNotice reads acked
                       or expired (never pending/absent), and a gang
                       wearing DisruptionTarget=True still carries its
                       notice (grove_tpu/disruption)
- **ttr-stability**    time-to-ready p99 stays within a drift factor
                       of the first cycle's (no degradation across
                       cycles — the soak signal)
- **lock-order**       under GROVE_LOCKDEP=1, the witnessed-lock
                       acquisition graph stays acyclic and no blocking
                       call runs under a witnessed lock
                       (grove_tpu/analysis/lockdep.py)
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Callable

from grove_tpu.api import (
    Node,
    Pod,
    PodClique,
    PodCliqueScalingGroup,
    PodCliqueSet,
    PodGang,
    SliceReservation,
    constants as c,
)
from grove_tpu.api.meta import is_condition_true
from grove_tpu.runtime.errors import GroveError, NotFoundError
from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.timescale import scaled


@dataclasses.dataclass
class Violation:
    invariant: str
    subject: str
    detail: str

    def __str__(self) -> str:
        return f"[{self.invariant}] {self.subject}: {self.detail}"


def _poll_until_empty(probe: Callable[[], list[Violation]],
                      deadline_s: float,
                      interval: float = 0.1) -> list[Violation]:
    """Re-run ``probe`` until it reports nothing or the (already
    scaled) deadline passes; transients get the grace, a stuck state
    does not."""
    deadline = time.time() + deadline_s
    found = probe()
    while found and time.time() < deadline:
        time.sleep(interval)
        found = probe()
    return found


class InvariantChecker:
    def __init__(self, cluster, namespace: str | None = None,
                 bind_deadline_s: float = 10.0,
                 owner_deadline_s: float = 8.0,
                 diagnosis_grace_s: float = 5.0,
                 diagnosis_staleness_s: float = 30.0,
                 gauge_deadline_s: float = 8.0,
                 ttr_drift_factor: float = 10.0,
                 ttr_drift_floor_s: float = 3.0):
        """Deadlines are pre-scale seconds (each is multiplied by
        TIME_SCALE). ``ttr_drift_factor`` is deliberately loose: this
        container's CPU share swings wildly between minutes (CHANGES.md
        PR 7), so the drift check catches collapse, not jitter — and
        ``ttr_drift_floor_s`` (scaled) keeps a fast-but-ratio-noisy
        sample (80ms -> 900ms) from counting as degradation: a drift
        violation needs the last cycle to be both RELATIVELY and
        ABSOLUTELY slow."""
        self.cluster = cluster
        self.client = cluster.client
        self.namespace = namespace
        self.bind_deadline = scaled(bind_deadline_s)
        self.owner_deadline = scaled(owner_deadline_s)
        self.diagnosis_grace = scaled(diagnosis_grace_s)
        self.diagnosis_staleness = scaled(diagnosis_staleness_s)
        self.gauge_deadline = scaled(gauge_deadline_s)
        self.ttr_drift_factor = ttr_drift_factor
        self.ttr_drift_floor = scaled(ttr_drift_floor_s)
        self.log = get_logger("chaos.invariants")
        # Per-cycle time-to-ready samples (seconds), appended by the
        # scenario runner via record_cycle_ttr.
        self.ttr_cycles: list[list[float]] = []

    # ---- individual invariants ------------------------------------------

    def check_gang_binding(self) -> list[Violation]:
        """Gang atomicity: a gang whose pods are part-bound must
        converge to fully bound (or fully unbound, e.g. preempted) —
        a partial bind that persists past the deadline is exactly the
        state gang scheduling exists to prevent."""

        def probe() -> list[Violation]:
            out: list[Violation] = []
            pods = [p for p in self.client.list(Pod, self.namespace)
                    if p.meta.deletion_timestamp is None]
            by_gang: dict[str, list[Pod]] = {}
            for p in pods:
                gang = p.meta.labels.get(c.LABEL_PODGANG_NAME, "")
                if gang:
                    by_gang.setdefault(
                        f"{p.meta.namespace}/{gang}", []).append(p)
            for key, members in by_gang.items():
                bound = [bool(p.status.node_name) for p in members]
                if any(bound) and not all(bound):
                    out.append(Violation(
                        "gang-binding", key,
                        f"partially bound: {sum(bound)}/{len(bound)} "
                        "pods placed"))
            return out

        return _poll_until_empty(probe, self.bind_deadline)

    def check_live_owner(self) -> list[Violation]:
        """No orphan survives: every managed object's controller owner
        must exist with a matching uid. A pod outliving its clique (or
        a clique its PCS) past the deadline means cascade deletion or
        the expectations barrier leaked."""
        kinds = {"PodClique": PodClique, "PodCliqueSet": PodCliqueSet,
                 "PodCliqueScalingGroup": PodCliqueScalingGroup,
                 "PodGang": PodGang}

        def probe() -> list[Violation]:
            out: list[Violation] = []
            live_uids: dict[tuple[str, str, str], str] = {}
            for kind, cls in kinds.items():
                for obj in self.client.list(cls, self.namespace):
                    if obj.meta.deletion_timestamp is None:
                        live_uids[(kind, obj.meta.namespace,
                                   obj.meta.name)] = obj.meta.uid
            objs = [(f"Pod {p.meta.namespace}/{p.meta.name}", p)
                    for p in self.client.list(Pod, self.namespace)]
            for kind, cls in kinds.items():
                if kind == "PodCliqueSet":
                    continue  # PCSes are roots
                objs.extend((f"{kind} {o.meta.namespace}/{o.meta.name}", o)
                            for o in self.client.list(cls, self.namespace))
            for label, obj in objs:
                if obj.meta.deletion_timestamp is not None:
                    continue
                refs = [r for r in obj.meta.owner_references
                        if r.kind in kinds]
                if not refs:
                    out.append(Violation("live-owner", label,
                                         "no controller owner reference"))
                    continue
                for ref in refs:
                    uid = live_uids.get(
                        (ref.kind, obj.meta.namespace, ref.name))
                    if uid is None:
                        out.append(Violation(
                            "live-owner", label,
                            f"owner {ref.kind}/{ref.name} is gone"))
                    elif ref.uid and uid != ref.uid:
                        out.append(Violation(
                            "live-owner", label,
                            f"owner {ref.kind}/{ref.name} uid changed "
                            f"(stale generation: {ref.uid} != {uid})"))
            return out

        return _poll_until_empty(probe, self.owner_deadline)

    def check_pending_diagnosis(self) -> list[Violation]:
        """Explainability never rots: a gang that has been pending
        longer than the grace must carry a PlacementDiagnosis whose
        last attempt is recent — 'my gang is stuck and nothing says
        why' is itself an incident (PR 5's contract)."""
        import os
        if os.environ.get("GROVE_EXPLAIN", "1") == "0":
            return []

        def probe() -> list[Violation]:
            out: list[Violation] = []
            now = time.time()
            for gang in self.client.list(PodGang, self.namespace):
                if gang.meta.deletion_timestamp is not None:
                    continue
                if is_condition_true(gang.status.conditions,
                                     c.COND_SCHEDULED):
                    continue
                age = now - (gang.meta.creation_timestamp or now)
                if age < self.diagnosis_grace:
                    continue
                key = f"{gang.meta.namespace}/{gang.meta.name}"
                diag = gang.status.last_diagnosis
                if diag is None:
                    out.append(Violation(
                        "pending-diagnosis", key,
                        f"pending {age:.1f}s with no diagnosis"))
                elif now - diag.last_attempt_time > self.diagnosis_staleness:
                    out.append(Violation(
                        "pending-diagnosis", key,
                        f"diagnosis stale: last attempt "
                        f"{now - diag.last_attempt_time:.1f}s ago "
                        f"(> {self.diagnosis_staleness:.1f}s)"))
            return out

        # Pending gangs re-attempt on scheduler sweeps; give one sweep
        # of grace before calling the diagnosis stale.
        return _poll_until_empty(probe, self.diagnosis_grace)

    def check_no_duplicates(self) -> list[Violation]:
        """The expectations hazard, checked directly: within one
        PodClique no two live pods may share a pod index, and the pod
        count must not exceed the clique's spec — more pods than asked
        for is a double-create that slipped the barrier."""

        def probe() -> list[Violation]:
            out: list[Violation] = []
            cliques = {(q.meta.namespace, q.meta.name): q
                       for q in self.client.list(PodClique, self.namespace)}
            by_clique: dict[tuple[str, str], list[Pod]] = {}
            for p in self.client.list(Pod, self.namespace):
                if p.meta.deletion_timestamp is not None:
                    continue
                pclq = p.meta.labels.get(c.LABEL_PCLQ_NAME, "")
                if pclq:
                    by_clique.setdefault(
                        (p.meta.namespace, pclq), []).append(p)
            for key, pods in by_clique.items():
                seen: dict[str, str] = {}
                for p in pods:
                    idx = p.meta.labels.get(c.LABEL_POD_INDEX, "")
                    if idx in seen:
                        out.append(Violation(
                            "no-duplicates", f"PodClique {key[0]}/{key[1]}",
                            f"pods {seen[idx]} and {p.meta.name} share "
                            f"index {idx} (double-create)"))
                    seen[idx] = p.meta.name
                q = cliques.get(key)
                if q is not None and len(pods) > q.spec.replicas:
                    out.append(Violation(
                        "no-duplicates", f"PodClique {key[0]}/{key[1]}",
                        f"{len(pods)} live pods exceed spec.replicas="
                        f"{q.spec.replicas}"))
            return out

        return _poll_until_empty(probe, self.owner_deadline)

    def check_gauge_consistency(self) -> list[Violation]:
        """The observability plane must agree with the store: per-kind
        totals of grove_state_objects (fed from informer caches) match
        a direct store list. A persistent mismatch means the caches —
        which every controller reads — have diverged."""
        from grove_tpu.runtime.metrics import parse_counters

        kinds = {"Pod": Pod, "PodGang": PodGang, "PodClique": PodClique,
                 "PodCliqueSet": PodCliqueSet, "Node": Node}

        def probe() -> list[Violation]:
            out: list[Violation] = []
            text = self.cluster.manager.metrics_text()
            gauges = parse_counters(text, "grove_state_objects")
            per_kind: dict[str, float] = {}
            for labels, value in gauges.items():
                kind = dict(labels).get("kind", "")
                per_kind[kind] = per_kind.get(kind, 0.0) + value
            for kind, cls in kinds.items():
                want = len(self.client.list(cls, namespace=None))
                got = per_kind.get(kind, 0.0)
                if int(got) != want:
                    out.append(Violation(
                        "gauge-consistency", kind,
                        f"grove_state_objects sums to {got:.0f}, store "
                        f"holds {want}"))
            return out

        return _poll_until_empty(probe, self.gauge_deadline)

    def check_defrag_holds(self) -> list[Violation]:
        """Capacity holds never dangle: every SliceReservation created
        as a defrag migration hold or roll-safe slot hold (the
        hold-for-gang label) must (a) protect a gang that still exists
        and (b) be the reservation that gang's reuse-reservation-ref
        annotation names. A hold that outlives either pointer fences a
        slice nobody will ever unfence — capacity leaked until the TTL
        backstop, invisible to the gang it was taken for."""

        def probe() -> list[Violation]:
            out: list[Violation] = []
            reservations = self.client.list(SliceReservation,
                                            self.namespace)
            live = {(r.meta.namespace, r.meta.name) for r in reservations}
            for rsv in reservations:
                if rsv.meta.deletion_timestamp is not None:
                    continue
                gname = rsv.meta.labels.get(c.LABEL_HOLD_FOR_GANG)
                if not gname:
                    continue    # PCS-template reservations: not holds
                key = (f"SliceReservation "
                       f"{rsv.meta.namespace}/{rsv.meta.name}")
                try:
                    gang = self.client.get(PodGang, gname,
                                           rsv.meta.namespace)
                except NotFoundError:
                    out.append(Violation(
                        "defrag-holds", key,
                        f"protected gang {gname} is gone but the hold "
                        "still fences its slices"))
                    continue
                ref = gang.meta.annotations.get(
                    c.ANNOTATION_RESERVATION_REF, "")
                if ref != rsv.meta.name:
                    out.append(Violation(
                        "defrag-holds", key,
                        f"gang {gname} references {ref!r}, not this "
                        "hold — it will never be consumed or released"))
            # The reverse pointer: a gang whose annotation names a
            # reservation that no longer exists stays pinned-looking on
            # every surface and defrag-ineligible forever (the TTL
            # expiry path clears it; persisting is a leak).
            for gang in self.client.list(PodGang, self.namespace):
                if gang.meta.deletion_timestamp is not None:
                    continue
                ref = gang.meta.annotations.get(
                    c.ANNOTATION_RESERVATION_REF, "")
                if ref and (gang.meta.namespace, ref) not in live:
                    out.append(Violation(
                        "defrag-holds",
                        f"PodGang {gang.meta.namespace}/{gang.meta.name}",
                        f"reuse-reservation-ref {ref!r} names a "
                        "reservation that no longer exists"))
            return out

        return _poll_until_empty(probe, self.owner_deadline)

    def check_disruption_contract(self) -> list[Violation]:
        """The planned-eviction audit (grove_tpu/disruption): a gang
        whose notice was stamped evicted must show barrier acked or
        expired — an eviction that proceeded while the barrier still
        read pending (or with no notice behind a DisruptionTarget
        condition) broke the one promise the contract makes. Both
        directions get the usual settling grace: the condition mirror
        rides scheduler status writes and can lag a just-cleared
        notice."""
        from grove_tpu.disruption.contract import notice_of

        def probe() -> list[Violation]:
            out: list[Violation] = []
            for gang in self.client.list(PodGang, self.namespace):
                if gang.meta.deletion_timestamp is not None:
                    continue
                key = f"PodGang {gang.meta.namespace}/{gang.meta.name}"
                notice = notice_of(gang)
                if notice is not None and notice.evicted_at \
                        and notice.barrier not in ("acked", "expired"):
                    out.append(Violation(
                        "disruption-contract", key,
                        f"evicted under notice {notice.id} with barrier "
                        f"{notice.barrier!r} — the eviction proceeded "
                        "without an ack or a deadline expiry"))
                if notice is None and is_condition_true(
                        gang.status.conditions, c.COND_DISRUPTION_TARGET):
                    out.append(Violation(
                        "disruption-contract", key,
                        "DisruptionTarget=True but the disruption-notice "
                        "annotation is absent — a barrier vanished "
                        "mid-flight"))
            return out

        return _poll_until_empty(probe, self.owner_deadline)

    def check_wire_convergence(
            self, wire_informers: dict | None) -> list[Violation]:
        """After watch-gap injection the wire informers must hold
        exactly the store's objects again — a cache that lost events
        and never reseeded serves holes to every consumer."""
        if not wire_informers:
            return []

        def probe() -> list[Violation]:
            out: list[Violation] = []
            for cls, (inf, _refl) in wire_informers.items():
                store_names = {(o.meta.namespace, o.meta.name)
                               for o in self.client.list(cls, namespace=None)}
                try:
                    cached = {(o.meta.namespace, o.meta.name)
                              for o in inf.lister().list(namespace=None)}
                except (GroveError, NotFoundError):
                    cached = set()
                if cached != store_names:
                    missing = store_names - cached
                    extra = cached - store_names
                    out.append(Violation(
                        "wire-convergence", cls.KIND,
                        f"cache diverged: missing={sorted(missing)[:3]} "
                        f"extra={sorted(extra)[:3]} "
                        f"({len(cached)} cached vs {len(store_names)})"))
            return out

        return _poll_until_empty(probe, self.gauge_deadline)

    # ---- time-to-ready stability ----------------------------------------

    def record_cycle_ttr(self, samples: list[float]) -> None:
        self.ttr_cycles.append(list(samples))

    @staticmethod
    def _p99(samples: list[float]) -> float:
        if not samples:
            return 0.0
        s = sorted(samples)
        return s[min(len(s) - 1, math.ceil(0.99 * len(s)) - 1)]

    def ttr_drift(self) -> float:
        """Latest cycle's p99 over the first cycle's (1.0 = flat)."""
        cycles = [cyc for cyc in self.ttr_cycles if cyc]
        if len(cycles) < 2:
            return 1.0
        base = self._p99(cycles[0])
        if base <= 0:
            return 1.0
        return self._p99(cycles[-1]) / base

    def check_ttr_stability(self) -> list[Violation]:
        drift = self.ttr_drift()
        cycles = [cyc for cyc in self.ttr_cycles if cyc]
        last_p99 = self._p99(cycles[-1]) if cycles else 0.0
        if drift > self.ttr_drift_factor and last_p99 > self.ttr_drift_floor:
            return [Violation(
                "ttr-stability", "gang time-to-ready",
                f"p99 drifted x{drift:.1f} from cycle 1 to "
                f"{last_p99:.2f}s (> x{self.ttr_drift_factor:g} and > "
                f"{self.ttr_drift_floor:.1f}s floor) — the control "
                "plane is degrading across cycles")]
        return []

    # ---- lock-order witness (grove_tpu/analysis/lockdep.py) -------------

    def check_lock_order(self) -> list[Violation]:
        """When the run is under GROVE_LOCKDEP=1, the acquisition graph
        the witnessed locks recorded must be free of cycles and of
        blocking-calls-under-lock. No polling grace: a recorded
        violation is history, not a transient — it cannot converge
        away."""
        from grove_tpu.analysis import lockdep
        if not lockdep.enabled():
            return []
        return [Violation("lock-order", v.kind, v.detail)
                for v in lockdep.witness().check()]

    # ---- the sweep -------------------------------------------------------

    def sweep(self, wire_informers: dict | None = None,
              include_ttr: bool = True) -> list[Violation]:
        """Run every invariant; returns all violations (empty = green).
        Ordered cheap-transient-tolerant first so the polling graces
        overlap the cluster settling."""
        out: list[Violation] = []
        out += self.check_gang_binding()
        out += self.check_live_owner()
        out += self.check_no_duplicates()
        out += self.check_pending_diagnosis()
        out += self.check_defrag_holds()
        out += self.check_disruption_contract()
        out += self.check_gauge_consistency()
        out += self.check_wire_convergence(wire_informers)
        out += self.check_lock_order()
        if include_ttr:
            out += self.check_ttr_stability()
        for v in out:
            self.log.error("invariant violated: %s", v)
        return out
