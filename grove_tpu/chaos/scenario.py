"""Seeded scenario runner — fault schedules composed with workload
actions into named scenarios, plus the randomized ``mix`` soak.

Reproducibility contract: every random choice (which fault, which
node/slice, stagger timing, workload actions) flows from ONE
``random.Random(seed)``; the same seed against the same code replays
the same schedule, which is what makes a chaos failure debuggable
(``tools/chaos_soak.py --mix --seed N`` is a repro command, not a dice
roll). Wall-clock nondeterminism (thread interleaving) still varies —
the seed pins the ABUSE, not the weather.

A cycle is the compressed-time soak unit (soak_test.go's repeated
scale up/down analog):

  deploy probe gang -> inject faults (staggered) -> workload action
  (rolling update / PCSG scale pressure) -> hold the fault window ->
  heal -> wait recovery (probe Ready, standing workload Ready) ->
  delete probe -> settle -> invariant sweep

Between cycles the InvariantChecker sweeps the store and every debug
surface; the probe's time-to-ready (from PR 3 trace milestones) feeds
the cross-cycle p99-stability invariant.

``run_leader_kill`` is the separate HA acceptance scenario (ROADMAP
item 4 / proposal 0002): a child process runs the whole control plane
against a persistent state dir, is SIGKILLed mid-deploy, and THIS
process takes over as the standby (flock + lease takeover,
store/persist.py), proving no orphaned/duplicated pods and
reconcile resumed under a pinned budget.
"""

from __future__ import annotations

import os
import random
import statistics
import time

from grove_tpu.api import Node, Pod, PodCliqueSet, constants as c, new_meta
from grove_tpu.api.core import ContainerSpec
from grove_tpu.api.meta import is_condition_true, trace_id_of
from grove_tpu.api.podcliqueset import (
    AutoScalingConfig,
    PodCliqueSetSpec,
    PodCliqueSetTemplate,
    PodCliqueTemplate,
    ScalingGroupConfig,
    StartupType,
    TopologyConstraint,
)
from grove_tpu.chaos.faults import FAULT_REGISTRY, ChaosContext
from grove_tpu.chaos.invariants import InvariantChecker, Violation
from grove_tpu.runtime.errors import GroveError, NotFoundError
from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.timescale import TIME_SCALE, scaled

SLICE = TopologyConstraint(pack_level="slice", required=True)
POOL = TopologyConstraint(pack_level="pool", required=True)

# Named scenarios: which fault types every cycle composes. "mix" is
# special-cased (a seeded sample of MIX_FAULTS_PER_CYCLE types per
# cycle); "leader-kill" is the subprocess scenario (run_leader_kill).
SCENARIOS: dict[str, list[str]] = {
    "node-flap": ["node-heartbeat-loss", "node-delete"],
    "preemption-storm": ["preemption-storm"],
    # Spot-slice reclamation (grove_tpu/disruption): a slice is
    # reclaim-noticed mid-cycle, its gangs evacuate behind the
    # checkpoint barrier, heal withdraws + re-registers the capacity;
    # the disruption-contract invariant audits every eviction's barrier.
    "spot-reclaim": ["spot-reclaim"],
    # Overlapping planned disruptions: multi-slice reclaim notices plus
    # a rolling update in one window — barrier coalescing under stress.
    "disruption-storm": ["disruption-storm"],
    "watch-gap": ["watch-gap"],
    "autoscale-flap": ["autoscale-flap"],
    "agent-restart": ["agent-kill"],
    # In-process leadership transitions (grove_tpu/ha): rival fences,
    # manager demotes (queue drop + expectations clear), fence proven,
    # re-promotion warm-starts reconcile — every cycle. The subprocess
    # kill-the-leader bench is the separate "leader-kill" scenario
    # (run_leader_kill, tools/chaos_soak.py).
    "leadership": ["leader-kill"],
}
MIX_FAULTS_PER_CYCLE = 4


def _wait(predicate, timeout_s: float, desc: str,
          interval: float = 0.05) -> None:
    """Poll until true or ``timeout_s * TIME_SCALE`` passes."""
    deadline = time.time() + scaled(timeout_s)
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise AssertionError(f"chaos: timed out waiting for {desc} "
                         f"({timeout_s}s x{TIME_SCALE:g})")


def _workload_pcs(name: str, autoscale_metric: str,
                  autoscale_target: float) -> PodCliqueSet:
    """The standing workload every scenario abuses: a steady standalone
    clique plus an elastic autoscaled scaling group (so preemption has
    scaled-gang victims and autoscale flapping has something to flap)."""
    return PodCliqueSet(
        meta=new_meta(name),
        spec=PodCliqueSetSpec(replicas=1, template=PodCliqueSetTemplate(
            topology=POOL,
            startup_type=StartupType.ANY_ORDER,
            cliques=[
                # REQUIRED slice pack again (the PR 8 wedge is fixed):
                # this clique rolls pod-by-pod under chaos, and the
                # roll-safe slot hold (grove_tpu/defrag) now fences the
                # freed slot so the replacement relands in place instead
                # of wedging as a forever-StragglerUnplaced when another
                # gang's replacement lands there mid-roll. The soak
                # proves the hold works under composed faults; the
                # dedicated repro is run_roll_wedge below.
                PodCliqueTemplate(name="steady", replicas=2,
                                  min_available=1, tpu_chips_per_pod=4,
                                  topology=SLICE,
                                  container=ContainerSpec(
                                      argv=["sleep", "inf"])),
                PodCliqueTemplate(name="elastic", replicas=1,
                                  min_available=1, tpu_chips_per_pod=4,
                                  topology=SLICE,
                                  container=ContainerSpec(
                                      argv=["sleep", "inf"])),
            ],
            # replicas=2 with min_available=1: instance 1 is a SCALED
            # (elastic) gang from the start — the preemption storm
            # needs a victim and scale-in needs something to prune.
            scaling_groups=[ScalingGroupConfig(
                name="inst", clique_names=["elastic"], replicas=2,
                min_available=1,
                auto_scaling=AutoScalingConfig(
                    min_replicas=1, max_replicas=3,
                    metric=autoscale_metric,
                    target_value=autoscale_target))],
        )))


def _probe_pcs(name: str) -> PodCliqueSet:
    """Per-cycle probe: one fresh 2-pod gang whose create->Ready time
    (trace milestones) is the cross-cycle stability signal."""
    return PodCliqueSet(
        meta=new_meta(name),
        spec=PodCliqueSetSpec(replicas=1, template=PodCliqueSetTemplate(
            topology=POOL,
            startup_type=StartupType.ANY_ORDER,
            cliques=[PodCliqueTemplate(
                name="probe", replicas=2, min_available=2,
                tpu_chips_per_pod=4, topology=SLICE,
                container=ContainerSpec(argv=["sleep", "inf"]))])))


class ScenarioRunner:
    """Owns the cluster under chaos, the fault set, and the checker."""

    def __init__(self, scenario: str = "mix", seed: int = 0,
                 cycles: int = 5, slices: int = 6,
                 autoscale_target: float = 10.0,
                 ttr_drift_factor: float = 10.0,
                 ttr_drift_floor_s: float = 3.0,
                 rolling_every: int = 2,
                 dump_fn=None):
        if scenario != "mix" and scenario not in SCENARIOS:
            raise ValueError(
                f"unknown scenario {scenario!r}; choose from "
                f"{sorted(SCENARIOS)} or 'mix' (leader-kill runs via "
                "run_leader_kill)")
        self.scenario = scenario
        self.seed = seed
        self.cycles = cycles
        self.slices = slices
        self.autoscale_target = autoscale_target
        self.ttr_drift_factor = ttr_drift_factor
        self.ttr_drift_floor_s = ttr_drift_floor_s
        self.rolling_every = rolling_every
        self.dump_fn = dump_fn
        self.rng = random.Random(seed)
        self.log = get_logger("chaos.scenario")
        self.cluster = None
        self.server = None
        self.ctx: ChaosContext | None = None
        self.checker: InvariantChecker | None = None
        self.wire_informers: dict = {}
        self._pump_stop = None
        self._roll_generation = 0
        self.fault_types_used: set[str] = set()
        # Mid-chaos probe recovery times (ms) per cycle — reported, but
        # excluded from the drift invariant (they measure the faults).
        self.chaos_ttr_ms: list[float] = []

    # ---- lifecycle -------------------------------------------------------

    def setup(self) -> None:
        from grove_tpu.api.config import OperatorConfiguration
        from grove_tpu.cluster import new_cluster
        from grove_tpu.runtime.informer import wire_informer
        from grove_tpu.server import ApiServer
        from grove_tpu.store.httpclient import FAULT_INJECT_ENV, HttpClient
        from grove_tpu.topology.fleet import FleetSpec, SliceSpec

        # The chaos opt-in, restored by teardown(): the env gate must
        # not stay open for whatever else runs in this process after.
        self._prev_fault_env = os.environ.get(FAULT_INJECT_ENV)
        self._fault_env = FAULT_INJECT_ENV
        os.environ[FAULT_INJECT_ENV] = "1"
        cfg = OperatorConfiguration()
        # Compressed time: tight detection/decision cadences so a cycle
        # is seconds, not the production-tuned minutes.
        cfg.node_lifecycle.grace_seconds = 1.0
        cfg.node_lifecycle.sync_period_seconds = 0.2
        cfg.autoscaler.sync_period_seconds = 0.3
        cfg.autoscaler.scale_down_stabilization_seconds = 1.5
        # 2x4 slices: 2 hosts / 8 chips each — one probe or steady gang
        # packs a slice; the elastic instance takes half of one.
        self.cluster = new_cluster(config=cfg, fleet=FleetSpec(slices=[
            SliceSpec(generation="v5e", topology="2x4",
                      count=self.slices)]))
        self.cluster.start()
        self.server = ApiServer(self.cluster, port=0)
        self.server.start()
        base = f"http://127.0.0.1:{self.server.port}"
        http = HttpClient(base)
        # Wire informer: a watch-fed consumer whose 410-gap recovery the
        # watch-gap fault exercises and the convergence invariant proves.
        inf, refl = wire_informer(http, PodCliqueSet, poll_timeout=2.0)
        refl.start()
        self.wire_informers = {PodCliqueSet: (inf, refl)}
        self._reflector = refl
        self.ctx = ChaosContext(
            self.cluster, self.rng, base_url=base, http=http,
            wire_informers=self.wire_informers,
            workload_pcs="soak",
            workload_pcsg="soak-0-inst",
            autoscale_target=self.autoscale_target)
        self.checker = InvariantChecker(
            self.cluster, ttr_drift_factor=self.ttr_drift_factor,
            ttr_drift_floor_s=self.ttr_drift_floor_s)

        client = self.cluster.client
        client.create(_workload_pcs("soak", self.ctx.autoscale_metric,
                                    self.autoscale_target))
        _wait(lambda: self._workload_ready(), 30.0,
              "standing workload up")
        self._start_traffic_pump()

    def teardown(self) -> None:
        if self._pump_stop is not None:
            self._pump_stop.set()
        if getattr(self, "_reflector", None) is not None:
            self._reflector.stop()
        if self.server is not None:
            self.server.stop()
        if self.cluster is not None:
            self.cluster.stop()
        if getattr(self, "_fault_env", None):
            if self._prev_fault_env is None:
                os.environ.pop(self._fault_env, None)
            else:
                os.environ[self._fault_env] = self._prev_fault_env

    # ---- workload actions (the soak's scale up/down analog) -------------

    def _workload_ready(self) -> bool:
        client = self.cluster.client
        try:
            pcs = client.get(PodCliqueSet, "soak")
        except NotFoundError:
            return False
        if pcs.status.available_replicas < 1:
            return False
        pods = [p for p in client.list(
            Pod, selector={c.LABEL_PCS_NAME: "soak"})
            if p.meta.deletion_timestamp is None]
        return bool(pods) and all(
            is_condition_true(p.status.conditions, c.COND_READY)
            for p in pods)

    def _start_traffic_pump(self) -> None:
        """Sustained loadgen traffic: a background reporter pushing a
        seeded noisy-but-steady scaling signal through /metrics/push at
        engine cadence — the registry must never go stale mid-soak, and
        the autoscaler always has live signal to act on."""
        import threading
        self._pump_stop = threading.Event()
        stop = self._pump_stop
        ctx = self.ctx
        pump_rng = random.Random(self.seed ^ 0x5EED)
        # 1.5x target sustains desired=2 instances (ceil(15/10)), so
        # the standing scaled gang survives quiet cycles; the flap
        # fault's spike (x3 target) pushes the sum to desired=3+.
        base = self.autoscale_target * 1.5

        def pump() -> None:
            while not stop.is_set():
                value = max(0.0, pump_rng.gauss(base, base * 0.1))
                ctx.push_metric(value, reporter="chaos-pump")
                stop.wait(0.4)

        threading.Thread(target=pump, name="chaos-traffic",
                         daemon=True).start()

    def _rolling_update(self) -> None:
        """Template edit on the standing workload: every pod rolls in
        place (pod-level rolling update) while faults fire around it."""
        client = self.cluster.client
        self._roll_generation += 1
        for _ in range(5):
            try:
                pcs = client.get(PodCliqueSet, "soak")
                for t in pcs.spec.template.cliques:
                    t.container.env["CHAOS_ROLL"] = str(
                        self._roll_generation)
                client.update(pcs)
                self.log.info("chaos: rolling update -> generation %d",
                              self._roll_generation)
                return
            except GroveError:
                time.sleep(0.05)   # conflict: re-read and retry
        self.log.warning("chaos: rolling update generation %d never "
                         "landed (5 conflicts) — this cycle rolls "
                         "nothing", self._roll_generation)

    # ---- the cycle -------------------------------------------------------

    def _cycle_faults(self) -> list:
        if self.scenario == "mix":
            names = self.rng.sample(sorted(FAULT_REGISTRY),
                                    k=MIX_FAULTS_PER_CYCLE)
        else:
            names = list(SCENARIOS[self.scenario])
        # fault_types_used is recorded at successful INJECTION (in
        # run_cycle), not here: a fault that no-opped or raised must
        # not count toward the ">=4 types mixed" acceptance.
        return [FAULT_REGISTRY[n]() for n in names]

    def run_cycle(self, i: int) -> list[Violation]:
        client = self.cluster.client
        ctx = self.ctx
        faults = self._cycle_faults()
        probe = f"probe-c{i}"
        self.log.info("chaos cycle %d: faults=%s",
                      i, [f.name for f in faults])

        t_deploy = time.time()
        client.create(_probe_pcs(probe))

        injected = []
        for f in faults:
            # Appended BEFORE inject: heal is repeat-safe even for an
            # unfired fault, and an inject that raises after partially
            # mutating the cluster must still be healed.
            injected.append(f)
            try:
                fired = f.inject(ctx)
                if fired:
                    self.fault_types_used.add(f.name)
                else:
                    self.log.warning("fault %s did not fire (no-op "
                                     "inject); not counted", f.name)
            except Exception as e:  # noqa: BLE001 — an unfirable fault
                self.log.warning("fault %s inject failed: %s", f.name, e)
                # must not kill the soak; the cycle runs short one fault

            time.sleep(self.rng.uniform(0.0, 0.2))

        # Every rolling_every-th cycle (1 = every cycle; the modulus
        # comparison is against rolling_every-1 so 1 actually fires —
        # "i % 1 == 1" never would).
        if self.rolling_every and \
                i % self.rolling_every == self.rolling_every - 1:
            self._rolling_update()

        # Hold the fault window (compressed): long enough for detection
        # cadences (grace 1s) to fire, short enough to soak many cycles.
        time.sleep(scaled(self.rng.uniform(1.2, 2.0)))

        for f in reversed(injected):
            try:
                f.heal(ctx)
            except Exception as e:  # noqa: BLE001
                self.log.warning("fault %s heal failed: %s", f.name, e)

        # Recovery: the probe reaches Ready despite everything above.
        _wait(lambda: client.get(PodCliqueSet, probe)
              .status.available_replicas >= 1, 40.0,
              f"{probe} available after chaos")
        chaos_ttr = self._probe_ttr(probe, t_deploy)
        self.chaos_ttr_ms.append(round(chaos_ttr * 1e3, 1))
        _wait(self._workload_ready, 40.0, "standing workload recovered")

        # Drop the probe (the scale-down half of the soak cycle).
        client.delete(PodCliqueSet, probe)
        _wait(lambda: not client.list(
            Pod, selector={c.LABEL_PCS_NAME: probe}), 20.0,
            f"{probe} pods pruned")

        # Pulse probe: a CLEAN post-heal deploy every cycle — same
        # conditions each time, so its create->Ready is the cross-cycle
        # stability signal. (The chaos probe's time measures the fault
        # window it deployed into — a per-cycle random quantity that
        # cannot feed a drift ratio.)
        pulse = f"pulse-c{i}"
        t_pulse = time.time()
        client.create(_probe_pcs(pulse))
        _wait(lambda: client.get(PodCliqueSet, pulse)
              .status.available_replicas >= 1, 30.0,
              f"{pulse} available on a healed fleet")
        pulse_ttr = self._probe_ttr(pulse, t_pulse)
        client.delete(PodCliqueSet, pulse)
        _wait(lambda: not client.list(
            Pod, selector={c.LABEL_PCS_NAME: pulse}), 20.0,
            f"{pulse} pods pruned")
        self.cluster.manager.wait_idle(timeout=scaled(10.0), settle=0.2)

        self.checker.record_cycle_ttr([pulse_ttr])
        return self.checker.sweep(wire_informers=self.wire_informers)

    def _probe_ttr(self, name: str, t_deploy: float) -> float:
        """Create->Ready seconds from the PR 3 trace milestones; falls
        back to the measured wall window when the milestone is missing
        (which the trace smoke, not this harness, guards)."""
        try:
            tid = trace_id_of(self.cluster.client.get(PodCliqueSet, name))
            data = self.cluster.client.debug_traces(tid)
            miles = {m["subject"]: m["phases"]
                     for m in data["milestones"]}
            phases = miles.get(f"default/{name}-0", {})
            t0 = data["starts"].get(tid, phases.get("gang_created"))
            if t0 is not None and "ready" in phases:
                return phases["ready"] - t0
        except (GroveError, NotFoundError, KeyError, TypeError):
            pass
        return time.time() - t_deploy

    def run(self) -> dict:
        """Full scenario run; returns the report dict (see
        tools/chaos_soak.py). Violations stop the run at the failing
        cycle — the cluster is left to the dump hook, then torn down."""
        violations: list[Violation] = []
        cycles_ok = 0

        def dump() -> None:
            if self.dump_fn is not None and self.cluster is not None:
                try:
                    self.dump_fn(self.cluster)
                except Exception:  # noqa: BLE001 — diagnostics must
                    self.log.exception("diagnostics dump failed")

        try:
            self.setup()   # inside the try: a half-built cluster (e.g.
            # the workload-up wait timing out on a throttled box) must
            # still tear its threads/server down, not leak them into
            # the rest of the process.
            for i in range(self.cycles):
                violations = self.run_cycle(i)
                if violations:
                    dump()
                    break
                cycles_ok += 1
        except BaseException:
            # A recovery-wait timeout is evidence too: dump the live
            # cluster before teardown destroys the stuck state.
            dump()
            raise
        finally:
            self.teardown()
        ttrs = [t for cyc in self.checker.ttr_cycles for t in cyc]
        return {
            "scenario": self.scenario,
            "seed": self.seed,
            "cycles": self.cycles,
            "cycles_ok": cycles_ok,
            "fault_types_used": sorted(self.fault_types_used),
            "violations": [str(v) for v in violations],
            "chaos_ttr_ms": list(self.chaos_ttr_ms),
            "ttr_ms": [round(t * 1e3, 1) for t in ttrs],
            "ttr_p50_ms": round(statistics.median(ttrs) * 1e3, 1)
            if ttrs else 0.0,
            "ttr_p99_ms": round(
                InvariantChecker._p99(ttrs) * 1e3, 1) if ttrs else 0.0,
            "ttr_p99_drift": round(self.checker.ttr_drift(), 3),
        }


# ---- roll-wedge: the PR 8 scheduling-wedge repro ------------------------


def _wedge_pcs(name: str, pods: int, chips: int,
               required: bool = True,
               min_available: int | None = None) -> PodCliqueSet:
    return PodCliqueSet(
        meta=new_meta(name),
        spec=PodCliqueSetSpec(replicas=1, template=PodCliqueSetTemplate(
            startup_type=StartupType.ANY_ORDER,
            cliques=[PodCliqueTemplate(
                name="w", replicas=pods,
                min_available=(pods if min_available is None
                               else min_available),
                tpu_chips_per_pod=chips,
                topology=TopologyConstraint(pack_level="slice",
                                            required=required),
                container=ContainerSpec(argv=["sleep", "inf"]))])))


def run_roll_wedge(defrag_on: bool = True, attempts: int = 3,
                   converge_s: float = 30.0) -> dict:
    """Reproduce the PR 8 roll-wedge through public surfaces and assert
    the defrag subsystem's verdict on it.

    Shape: a full 2-slice fleet — a REQUIRED slice-packed 2-pod gang
    ("wedge") owning slice A, a same-shaped blocker owning slice B, and
    a pending 1-pod gang ("squat", preferred pack) waiting for any free
    chips. A pod-level rolling update of the wedge gang then frees one
    slot per replaced pod — the exact window where, pre-defrag, the
    squat landed and the returning straggler deadlocked forever
    (StragglerUnplaced, docs/design/chaos-harness.md).

    ``defrag_on=True``: asserts the roll-safe slot hold keeps the slot
    fenced and the roll CONVERGES within the scaled deadline — every
    wedge pod back at the new hash, Ready, on one slice, no straggler
    diagnosis, hold released.

    ``defrag_on=False`` (GROVE_DEFRAG=0): asserts today's pre-defrag
    behavior is restored exactly — the wedge REPRODUCES within
    ``attempts`` rolls (the squat wins the freed slot and the wedge
    gang sticks as StragglerUnplaced). The race is real, so each
    attempt re-rolls until one wedges.
    """
    from grove_tpu.api import PodGang, SliceReservation
    from grove_tpu.api.config import OperatorConfiguration
    from grove_tpu.cluster import new_cluster
    from grove_tpu.defrag import DEFRAG_ENV
    from grove_tpu.topology.fleet import FleetSpec, SliceSpec

    log = get_logger("chaos.roll-wedge")
    prev_env = os.environ.get(DEFRAG_ENV)
    os.environ[DEFRAG_ENV] = "1" if defrag_on else "0"
    cfg = OperatorConfiguration()
    cfg.defrag.sync_period_seconds = 0.1
    cfg.defrag.cooldown_seconds = 0.0
    cluster = new_cluster(config=cfg, fleet=FleetSpec(slices=[
        SliceSpec(generation="v5e", topology="2x4", count=2)]))
    report: dict = {"defrag_on": defrag_on}
    try:
        with cluster:
            client = cluster.client

            def pods_of(name: str) -> list:
                return [p for p in client.list(
                    Pod, selector={c.LABEL_PCS_NAME: name})
                    if p.meta.deletion_timestamp is None]

            def all_ready(name: str, n: int, hash_: str | None = None
                          ) -> bool:
                ps = pods_of(name)
                return (len(ps) == n
                        and all(p.status.node_name for p in ps)
                        and all(is_condition_true(p.status.conditions,
                                                  c.COND_READY)
                                for p in ps)
                        and (hash_ is None or all(
                            p.meta.labels.get(c.LABEL_POD_TEMPLATE_HASH)
                            == hash_ for p in ps)))

            # Fill the fleet: wedge owns slice A, blocker owns slice B.
            client.create(_wedge_pcs("wedge", pods=2, chips=4,
                                     min_available=1))
            client.create(_wedge_pcs("blocker", pods=2, chips=4))
            _wait(lambda: all_ready("wedge", 2) and all_ready("blocker", 2),
                  30.0, "wedge + blocker gangs up (fleet full)")

            # The squatter: pending on a full fleet, wakes on any freed
            # chip (preferred pack — it takes whatever opens up).
            client.create(_wedge_pcs("squat", pods=1, chips=4,
                                     required=False))
            _wait(lambda: any(
                g.status.last_diagnosis is not None
                for g in client.list(PodGang,
                                     selector={c.LABEL_PCS_NAME: "squat"})),
                15.0, "squat gang pending with a diagnosis")

            def wedge_gang() -> "PodGang":
                return client.list(
                    PodGang, selector={c.LABEL_PCS_NAME: "wedge"})[0]

            def roll(generation: int) -> str:
                from grove_tpu.controllers.expected import generation_hash
                for _ in range(10):
                    try:
                        pcs = client.get(PodCliqueSet, "wedge")
                        for t in pcs.spec.template.cliques:
                            t.container.env["WEDGE_ROLL"] = str(generation)
                        return generation_hash(client.update(pcs))
                    except GroveError:
                        time.sleep(0.05)
                raise AssertionError("wedge roll edit kept conflicting")

            if defrag_on:
                target = roll(1)
                t0 = time.time()
                _wait(lambda: all_ready("wedge", 2, target), converge_s,
                      "required-pack roll to converge (no wedge)")
                gang = wedge_gang()
                diag = gang.status.last_diagnosis
                assert diag is None or diag.reason != "StragglerUnplaced", \
                    f"roll converged but straggler diagnosis stuck: {diag}"
                slices = {client.get(Node, p.status.node_name)
                          .meta.labels[c.NODE_LABEL_SLICE]
                          for p in pods_of("wedge")}
                assert len(slices) == 1, \
                    f"wedge gang split across slices {slices}"
                # The hold must release with the roll: no roll- hold
                # reservation left, annotation cleared.
                _wait(lambda: not [
                    r for r in client.list(SliceReservation)
                    if r.meta.labels.get(c.LABEL_HOLD_FOR_GANG)],
                    10.0, "roll hold released")
                report.update({
                    "converged": True,
                    "roll_s": round(time.time() - t0, 2),
                    "wedge_slices": sorted(slices),
                })
                log.info("roll-wedge (defrag on): converged in %.2fs",
                         report["roll_s"])
            else:
                wedged = False
                for attempt in range(1, attempts + 1):
                    target = roll(attempt)
                    deadline = time.time() + scaled(12.0)
                    while time.time() < deadline:
                        diag = wedge_gang().status.last_diagnosis
                        if diag is not None and \
                                diag.reason == "StragglerUnplaced":
                            wedged = True
                            break
                        if all_ready("wedge", 2, target):
                            break   # replacement won the race; re-roll
                        time.sleep(0.1)
                    if wedged:
                        break
                assert wedged, (
                    f"GROVE_DEFRAG=0 did not reproduce the wedge in "
                    f"{attempts} rolls — pre-defrag behavior changed")
                # The wedge is the OLD steady state: squat holds the
                # slot, the straggler stays diagnosed, nothing moves.
                time.sleep(scaled(2.0))
                diag = wedge_gang().status.last_diagnosis
                assert diag is not None \
                    and diag.reason == "StragglerUnplaced", \
                    f"wedge did not persist: {diag}"
                squat_bound = any(p.status.node_name
                                  for p in pods_of("squat"))
                report.update({"wedged": True, "attempt": attempt,
                               "squat_bound": squat_bound})
                log.info("roll-wedge (defrag off): wedged on roll %d "
                         "(squat bound=%s) — pre-defrag behavior intact",
                         attempt, squat_bound)
        report["ok"] = True
        return report
    finally:
        if prev_env is None:
            os.environ.pop(DEFRAG_ENV, None)
        else:
            os.environ[DEFRAG_ENV] = prev_env


# ---- leader-kill: the HA failover acceptance bench ----------------------

_LEADER_CHILD = """
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("GROVE_TEST_TIME_SCALE", "1.0")
from grove_tpu.api import Pod, PodCliqueSet, constants as c
from grove_tpu.api.core import ContainerSpec
from grove_tpu.api.meta import new_meta
from grove_tpu.api.podcliqueset import (PodCliqueSetSpec,
    PodCliqueSetTemplate, PodCliqueTemplate, StartupType)
from grove_tpu.cluster import new_cluster
from grove_tpu.topology.fleet import FleetSpec, SliceSpec

state_dir = {state_dir!r}
progress = {progress!r}
pods_per_gang = {pods_per_gang}
gangs = {gangs}
serve_port_file = {serve_port_file!r}

hosts = max(4, (pods_per_gang * gangs) // 64)
config = None
if serve_port_file:
    # Hot-standby variant: the leader serves HTTP so the standby can
    # mirror it; a system token lets the standby see Secret events
    # (an anonymous watch censors them, breaking mirror contiguity
    # and with it the warm-load fast path).
    from grove_tpu.api.config import OperatorConfiguration
    from grove_tpu.admission.authorization import OPERATOR_ACTOR
    config = OperatorConfiguration()
    config.server_auth.tokens["chaos-standby"] = OPERATOR_ACTOR
cl = new_cluster(config=config, state_dir=state_dir,
                 fleet=FleetSpec(slices=[
    SliceSpec(generation="v5e", topology="4x4",
              count=max(1, hosts // 4))]))
with cl:
    if serve_port_file:
        from grove_tpu.server import ApiServer
        srv = ApiServer(cl, port=0)
        srv.start()
        tmp = serve_port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(srv.port))
        os.replace(tmp, serve_port_file)
    # History phase: a full same-size deploy + teardown BEFORE the
    # measured one gives the state dir a production-depth WAL+snapshot
    # (creates, binds, readiness churn, cascade deletes — compaction
    # included once past the threshold). A control plane that dies has
    # usually been RUNNING; a takeover bench against a near-empty WAL
    # would hide exactly the load cost the hot standby exists to skip.
    def _mk(name):
        return PodCliqueSet(
            meta=new_meta(name),
            spec=PodCliqueSetSpec(replicas=gangs,
                                  template=PodCliqueSetTemplate(
                startup_type=StartupType.ANY_ORDER,
                cliques=[PodCliqueTemplate(
                    name="w", replicas=pods_per_gang,
                    min_available=pods_per_gang, tpu_chips_per_pod=0,
                    container=ContainerSpec(argv=["sleep", "inf"]))])))
    cl.client.create(_mk("ha-warmup"))
    deadline = time.time() + 300
    while time.time() < deadline:
        if cl.client.get(PodCliqueSet, "ha-warmup") \\
                .status.available_replicas >= gangs:
            break
        time.sleep(0.1)
    cl.client.delete(PodCliqueSet, "ha-warmup")
    # The drain gets its OWN deadline and must complete: teardown
    # deletes bleeding into the measured deploy would spend the kill
    # threshold on delete records and land the kill before the first
    # pod create.
    drain_deadline = time.time() + 180
    while time.time() < drain_deadline and cl.client.list(
            Pod, selector={{c.LABEL_PCS_NAME: "ha-warmup"}}):
        time.sleep(0.1)
    time.sleep(1.0)     # let trailing cascade deletes settle
    # Fold the warmup history into the snapshot NOW: the measured
    # deploy then starts with a fresh WAL, so the in-operation
    # compactor's rotation (threshold crossing) cannot land inside the
    # kill window and orphan a segment the takeover must fall back on.
    # Cold still pays the full snapshot decode; the mirror covers it.
    cl.manager.store.compact_now()
    # Deploy only once the bench is ready (hot variant: the standby
    # must be seeded and watching before the burst, as a real warm
    # replica would be; the parent touches the marker).
    ready_file = {ready_file!r}
    while ready_file and not os.path.exists(ready_file):
        time.sleep(0.02)
    cl.client.create(PodCliqueSet(
        meta=new_meta("ha-deploy"),
        spec=PodCliqueSetSpec(replicas=gangs,
                              template=PodCliqueSetTemplate(
            startup_type=StartupType.ANY_ORDER,
            cliques=[PodCliqueTemplate(
                name="w", replicas=pods_per_gang,
                min_available=pods_per_gang, tpu_chips_per_pod=0,
                container=ContainerSpec(argv=["sleep", "inf"]))]))))
    while True:
        n = len(cl.client.list(Pod,
                               selector={{c.LABEL_PCS_NAME: "ha-deploy"}}))
        tmp = progress + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(n))
        os.replace(tmp, progress)
        time.sleep(0.05)
"""


# The assassin: a DEDICATED process that watches the leader's WAL and
# SIGKILLs it at a record-count threshold. Neither the leader (whose
# GIL is saturated by the deploy) nor the bench parent (whose GIL a
# hot standby's mirror decode saturates) can deliver a timely kill —
# both biases land the kill AFTER the deploy completes in exactly one
# of the warm/cold variants, silently making them measure different
# recovery paths. A third process has no other load in either mode,
# and the WAL is appended SYNCHRONOUSLY inside every store write (the
# progress file the leader maintains lags by a whole GIL-stretched
# tick — hundreds of creates during a burst), so counting WAL records
# pins the kill within a few milliseconds of the threshold write. Its
# stamp is the authoritative t_kill.
_KILL_WATCHER = """
import os, signal, sys, time
wal, progress, pid, kill_records, stamp = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
    sys.argv[5])


def count() -> int:
    try:
        with open(wal, "rb") as f:
            return f.read().count(b"\\n")
    except OSError:
        return 0


# Anchor at the DEPLOY's start, not the process's: cluster bring-up
# (fleet nodes, topology) writes its own WAL records; the progress
# file appears when the leader has created the PodCliqueSet. Appends
# are accumulated as DELTAS because compaction rotates the live WAL
# (the line count drops to ~0 at every rotation — a raw threshold
# would never fire on a leader whose history phase compacted).
while not os.path.exists(progress):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        sys.exit(0)
    time.sleep(0.002)
prev = count()
appended = 0
while True:
    n = count()
    if n > prev:
        appended += n - prev
    prev = n                        # n < prev means a rotation reset
    if appended >= kill_records:
        with open(stamp + ".tmp", "w") as f:
            f.write(repr(time.time()))
        os.replace(stamp + ".tmp", stamp)
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        break
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        break                       # leader died early; nothing to kill
    time.sleep(0.002)
"""


def run_leader_kill(pods: int = 300, pods_per_gang: int = 12,
                    state_dir: str | None = None,
                    kill_fraction: float = 0.2,
                    resume_budget_s: float = 30.0,
                    deploy_timeout_s: float = 120.0,
                    hot_standby: bool = False) -> dict:
    """SIGKILL the manager mid-deploy; the standby fences and takes
    over (flock + lease, store/persist.py — proposal 0002's acceptance
    bench). Asserts: no orphaned pods, no duplicated pods, the deploy
    COMPLETES under the new leader, and reconcile observably resumed
    (first post-takeover pod create) within ``resume_budget_s``
    (TIME_SCALE-scaled).

    The leader is a real child process running the full control plane
    against ``state_dir``; this process plays the standby — a different
    pid, so the flock/lease takeover path is the genuine article.

    ``hot_standby=True`` is the grove_tpu/ha variant: the child also
    serves HTTP, this process runs a ``HotStandby`` mirroring it over
    the watch stream for the whole deploy, and takeover goes through
    ``HotStandby.promote()`` — fence (epoch bump), WAL-delta warm load
    from the mirror's rv, warm-start reconcile. The report gains
    ``mode``/``load`` so the bench can pin warm strictly faster than
    the cold path on the same seed."""
    import signal
    import subprocess
    import sys
    import tempfile
    import textwrap

    from grove_tpu.cluster import new_cluster
    from grove_tpu.store.store import Store

    gangs = pods // pods_per_gang
    assert gangs * pods_per_gang == pods, \
        f"pods={pods} must divide by pods_per_gang={pods_per_gang}"
    log = get_logger("chaos.leader-kill")
    workdir = tempfile.mkdtemp(prefix="chaos-leader-")
    log.info("leader-kill workdir (state dir + leader log): %s", workdir)
    state_dir = state_dir or os.path.join(workdir, "state")
    progress = os.path.join(workdir, "progress")
    port_file = os.path.join(workdir, "port") if hot_standby else ""
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    threshold = max(1, int(pods * kill_fraction))
    ready_file = os.path.join(workdir, "ready")
    child_code = textwrap.dedent(_LEADER_CHILD).format(
        state_dir=state_dir, progress=progress,
        pods_per_gang=pods_per_gang, gangs=gangs,
        serve_port_file=port_file, ready_file=ready_file)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    # Child output goes to a FILE, not pipes: the control plane logs
    # freely to stderr, and an undrained pipe buffer filling up would
    # block the child mid-deploy — a hang indistinguishable from the
    # failover regression this bench exists to catch. The file is also
    # the evidence to read when the child dies early.
    child_log_path = os.path.join(workdir, "leader.log")
    child_log = open(child_log_path, "wb")
    leader = subprocess.Popen([sys.executable, "-c", child_code], env=env,
                              stdout=child_log, stderr=child_log)
    kill_stamp = os.path.join(workdir, "killstamp")
    # Threshold in WAL records past the deploy's start: the deploy
    # phase is dominated by creates (pods + their gang/clique/pcs
    # parents), so records ≈ pods-created — undershooting keeps the
    # kill safely mid-deploy.
    watcher = subprocess.Popen(
        [sys.executable, "-c", _KILL_WATCHER,
         os.path.join(state_dir, "wal.jsonl"), progress,
         str(leader.pid), str(threshold), kill_stamp],
        env=env)
    hot = None
    try:
        def progress_count() -> int:
            try:
                with open(progress) as f:
                    return int(f.read().strip() or 0)
            except (OSError, ValueError):
                return 0

        def _leader_died(what: str) -> "AssertionError":
            child_log.flush()
            with open(child_log_path, "rb") as f:
                tail = f.read()[-2000:]
            return AssertionError(
                f"leader died before {what}: "
                f"{tail.decode(errors='replace')}")

        if hot_standby:
            # The standby warms up while the leader is alive: mirror
            # seeded from a full relist, then fed by the watch stream —
            # all the decode work promotion would otherwise pay.
            from grove_tpu.ha.standby import HotStandby
            _wait(lambda: leader.poll() is not None
                  or os.path.exists(port_file),
                  deploy_timeout_s, "leader HTTP server up")
            if leader.poll() is not None:
                raise _leader_died("serving")
            with open(port_file) as f:
                port = int(f.read().strip())
            hot = HotStandby(f"http://127.0.0.1:{port}",
                             state_dir=state_dir, token="chaos-standby",
                             replica="chaos-standby")
            hot.start()
        # Green-light the deploy (the child holds the PCS create until
        # the standby — when there is one — is seeded and watching).
        with open(ready_file + ".tmp", "w") as f:
            f.write("go")
        os.replace(ready_file + ".tmp", ready_file)
        # The watcher process SIGKILLs the leader at the threshold (see
        # _KILL_WATCHER for why neither this process nor the leader
        # can): wait for the death it delivers.
        _wait(lambda: leader.poll() is not None, deploy_timeout_s,
              f"the watcher to kill the leader at >= {threshold} pods",
              interval=0.005)
        if leader.returncode != -signal.SIGKILL:
            raise _leader_died(f"the kill point (exit "
                               f"{leader.returncode})")
        try:
            with open(kill_stamp) as f:
                t_kill = float(f.read().strip())
        except (OSError, ValueError):
            t_kill = time.time()    # stamp lost: parent detection time
        pods_at_kill = progress_count()
        log.info("leader SIGKILLed at %d/%d pods", pods_at_kill, pods)
    except BaseException:
        if leader.poll() is None:
            leader.kill()
        if watcher.poll() is None:
            watcher.kill()
        raise
    finally:
        child_log.close()
        try:
            watcher.wait(timeout=5)
        except subprocess.TimeoutExpired:
            watcher.kill()

    # Takeover: the kernel released the dead leader's flock. Cold path
    # loads snapshot+full-WAL into a fresh cluster; hot path promotes
    # the warm standby (fence -> WAL-delta load -> warm start). The
    # load phase is timed separately in both: it is the component the
    # warm path optimizes, and the end-to-end resume on a throttled
    # box is too noisy to show it alone.
    phases: dict = {}
    if hot is not None:
        standby = hot.promote()
        store = standby.manager.store
        phases = dict(hot.last_promotion)
        # promote() started the cluster, so a pod count here would
        # include post-start creates; the pre-start count is the
        # mirror's (what the new leader actually LOADED).
        loaded_pods = sum(1 for (k, _, _) in hot.mirror_snapshot()[0]
                          if k == "Pod")
    else:
        t_to = time.perf_counter()
        store = Store(state_dir=state_dir, takeover_wait=True)
        phases["load_s"] = round(time.perf_counter() - t_to, 4)
        standby = new_cluster(store=store)
        loaded_pods = len(standby.client.list(
            Pod, selector={c.LABEL_PCS_NAME: "ha-deploy"}))
        phases["total_s"] = round(time.perf_counter() - t_to, 4)
    client = standby.client
    sel = {c.LABEL_PCS_NAME: "ha-deploy"}
    report: dict = {
        "pods": pods, "gangs": gangs,
        "pods_at_kill": pods_at_kill,
        "pods_loaded": loaded_pods,
        "mode": "warm" if hot is not None else "cold",
        "epoch": store.fencing_epoch(),
        "load": dict(store._persister.last_load)
        if store._persister is not None else {},
        "phases": phases,
    }
    with standby:
        # Resumed = the new leader makes PROGRESS, not just loads: the
        # first post-takeover pod create proves controllers recomputed
        # expectations from live state and continued the deploy. When
        # the kill raced deploy completion (every pod already created),
        # progress means the PCS going fully Available instead.
        if loaded_pods < pods:
            _wait(lambda: len(client.list(Pod, selector=sel)) > loaded_pods,
                  resume_budget_s, "post-takeover reconcile progress")
        else:
            _wait(lambda: client.get(PodCliqueSet, "ha-deploy")
                  .status.available_replicas >= gangs,
                  resume_budget_s, "post-takeover availability")
        t_resumed = time.time()
        report["time_to_resumed_s"] = round(t_resumed - t_kill, 3)
        assert t_resumed - t_kill <= scaled(resume_budget_s), \
            (f"reconcile resumed in {t_resumed - t_kill:.1f}s, budget "
             f"{resume_budget_s}s x{TIME_SCALE:g}")

        _wait(lambda: client.get(PodCliqueSet, "ha-deploy")
              .status.available_replicas >= gangs, deploy_timeout_s,
              "deploy completes under the new leader")
        final = [p for p in client.list(Pod, selector=sel)
                 if p.meta.deletion_timestamp is None]
        assert len(final) == pods, \
            f"{len(final)} pods after failover, expected exactly {pods}"

        # Epoch fence proof (warm path — promotion bumped the epoch):
        # a write still stamped with the dead leader's term must be
        # REJECTED at the store, observably. This is the zombie-leader
        # guard the whole epoch machinery exists for.
        if report["epoch"] > 0:
            from grove_tpu.runtime.errors import FencedError
            from grove_tpu.store.client import Client as _Client
            probe = _Client(store)
            probe.epoch = report["epoch"] - 1
            try:
                probe.patch_status(PodCliqueSet, "ha-deploy", {})
                raise AssertionError(
                    "stale-epoch write ACCEPTED after promotion — the "
                    "zombie-leader fence is broken")
            except FencedError:
                report["fence_proven"] = True

        checker = InvariantChecker(standby)
        violations = (checker.check_live_owner()
                      + checker.check_no_duplicates()
                      + checker.check_gang_binding())
        report["violations"] = [str(v) for v in violations]
        assert not violations, \
            "invariants violated after failover:\n  " + "\n  ".join(
                str(v) for v in violations)
    report["ok"] = True
    log.info("leader-kill OK: resumed in %.2fs, %d pods, 0 violations",
             report["time_to_resumed_s"], pods)
    # A green run's state dir (full WAL+snapshot of a 300-pod deploy)
    # is just disk; a FAILED run's is evidence, so only success cleans
    # up — on failure the assertions above raise past this point and
    # the kept dir's path was logged at startup.
    import shutil
    shutil.rmtree(workdir, ignore_errors=True)
    return report


def run_prefill_replica_kill(prompts: int = 6, max_new: int = 8,
                             seed: int = 0) -> dict:
    """Kill the prefill tier of a GROVE_DISAGG pair at the worst
    moment — BETWEEN chunk completion and decode adoption, with
    finished payloads sitting unshipped in the outbox — and prove the
    two disagg invariants (ROADMAP's prefill-replica-kill):

    * **No leaked or double-freed blocks.** The decode tier's
      allocator passes ``check()`` immediately after the kill and
      again after the recovered run drains: the dead tier's in-flight
      payload blocks died with its pool (a killed replica's HBM is
      gone; nothing on the decode side ever referenced them), and
      recovery must not free them into anyone's list.
    * **Bitwise-identical tokens.** Every request re-prefills on the
      replacement tier and completes with exactly the token stream a
      mono ``PagedDecodeEngine`` produces for the same prompts —
      greedy re-prefill is deterministic, so a rid-keyed compare is
      exact, not statistical.

    The kill point is staged deliberately: the pair runs normally
    until the decode tier holds live adopted sequences (so recovery
    also proves in-flight decode work rides through the swap), then
    the prefill tier ticks WITHOUT the outbox pump until a payload is
    stranded mid-handoff. ``DisaggServing.replace_prefill`` is the
    recovery path under test — it may read the dead engine's host-side
    request metadata (the router's request log in a real deployment)
    but never its allocator."""
    import dataclasses as dc

    import jax
    import jax.numpy as jnp
    import numpy as np

    from grove_tpu.models import llama
    from grove_tpu.serving.engine import (DisaggServing, PagedDecodeEngine,
                                          PrefillEngine, make_disagg)

    log = get_logger("chaos.prefill-replica-kill")
    cfg = dc.replace(llama.CONFIGS["test-tiny"], dtype=jnp.float32,
                     max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    geom = dict(batch=4, block_size=8, prefill_chunk=8,
                host_sync_interval=4)
    rng = np.random.default_rng(seed)
    # Longest prompts last: they are still queued (prefill slots = 4)
    # when the early ones reach the decode tier, guaranteeing live
    # prefill work to strand at the kill point.
    lens = sorted(rng.integers(3, 28, size=prompts).tolist())
    toks = [rng.integers(1, cfg.vocab_size, size=n).astype(np.int32)
            for n in lens]

    def _drain(eng, want: int, budget: int = 600) -> None:
        while len(eng.completed) < want and budget > 0:
            eng.admit_from_queue()
            eng.step()
            budget -= 1
        eng.sync()
        assert len(eng.completed) >= want, \
            f"stalled: {len(eng.completed)}/{want} done within budget"

    # Reference: the mono engine on the same prompts (same submit
    # order => same rids on both sides).
    mono = PagedDecodeEngine(cfg, params, **geom)
    for t in toks:
        mono.submit(t, max_new_tokens=max_new)
    _drain(mono, prompts)
    expect = {r.rid: list(r.generated) for r in mono.completed}

    dis = make_disagg(cfg, params, **geom)
    for t in toks:
        dis.submit(t, max_new_tokens=max_new)
    # Phase A: run the pair normally until decode holds live work.
    guard = 200
    while not dis.decode._sched.running and guard > 0:
        dis.admit_from_queue()
        dis.step()
        guard -= 1
    assert dis.decode._sched.running, "decode tier never went live"
    # Phase B: tick ONLY the prefill tier (no outbox pump) until a
    # finished prefill is stranded mid-handoff.
    guard = 200
    while not dis.prefill.outbox and guard > 0:
        dis.admit_from_queue()
        dis.prefill.step()
        guard -= 1
    assert dis.prefill.outbox, "never reached a mid-handoff state"
    report: dict = {
        "prompts": prompts, "max_new": max_new, "seed": seed,
        "outbox_at_kill": len(dis.prefill.outbox),
        "blocks_in_flight": sum(len(p.blocks) for p in dis.prefill.outbox),
        "prefilling_at_kill": len(dis.prefill._sched.prefilling),
        "decode_live_at_kill": dis.decode._sched.live,
    }
    log.info("killing prefill tier: %d payload(s) mid-handoff, "
             "%d block(s) in flight, %d seq(s) live on decode",
             report["outbox_at_kill"], report["blocks_in_flight"],
             report["prefilling_at_kill"] + report["decode_live_at_kill"])

    # The kill + recovery: the old engine (pool, allocator, outbox
    # payloads) is dropped wholesale — nothing releases its blocks,
    # exactly like a SIGKILLed replica. Decode must be clean BEFORE
    # any recovery runs: adoption is all-or-nothing per payload.
    replacement = PrefillEngine(cfg, params, **geom)
    rescued = dis.replace_prefill(replacement)
    dis.decode._alloc.check()
    report["rescued"] = rescued
    assert rescued >= report["outbox_at_kill"], \
        "mid-handoff payloads were not rescued"

    _drain(dis, prompts)
    dis.decode._alloc.check()
    dis.prefill._alloc.check()
    assert not dis.decode._alloc._refs and not dis.prefill._alloc._refs, \
        "live block refs after drain — leaked handoff blocks"
    got = {r.rid: list(r.generated) for r in dis.completed}
    assert set(got) == set(expect), \
        f"rid sets diverged: {sorted(got)} vs {sorted(expect)}"
    mismatched = [rid for rid in expect if got[rid] != expect[rid]]
    assert not mismatched, \
        f"token streams diverged after re-prefill for rids {mismatched}"
    report.update({
        "completed": len(got),
        "tokens_bitwise_identical": True,
        "decode_allocator": dis.decode._alloc.payload(),
        "handoff": dis.handoff_view(),
        "ok": True,
    })
    log.info("prefill-replica-kill OK: %d rescued, %d/%d requests "
             "bitwise-identical to mono, allocators clean",
             rescued, len(got), prompts)
    return report
