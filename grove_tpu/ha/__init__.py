"""HA control plane — hot-standby managers with epoch-fenced writes.

ROADMAP item 4 / proposal 0002's full build-out: the reference operator
runs leader-elected (`operator/cmd/main.go` → Lease-based election);
this package turns the single-process manager into a 2–3 replica
control plane:

- ``election.LeaderElector`` — campaign → renew → release over the
  state dir's flock + lease (store/persist.py), with a monotonic
  **fencing epoch** persisted through snapshot+WAL. Every control-plane
  write carries its writer's epoch and the Store rejects stale-epoch
  writes (``FencedError``) — closing the zombie-leader race SIGKILL
  fencing alone cannot (a wedged leader can wake up mid-write after
  the standby promotes).
- ``standby.HotStandby`` — a warm replica: a wire mirror of every kind
  kept current over ``resumable_watch_events`` against the leader,
  controllers and scheduler not running. On ``promote()`` it fences,
  replays only the WAL delta since its last seen rv
  (``StatePersister.load_warm``), and warm-starts reconcile.
- ``standby.StandbyServer`` — serves reads from the mirror; mutating
  verbs get 503 + a leader hint (clients follow it, see
  ``HttpClient`` / ``cli._http``).

``GROVE_HA=0`` disables the whole subsystem at runtime: no epoch is
ever bumped or stamped, the fence check no-ops, and a single-replica
start behaves exactly as before this package existed.

See docs/design/ha.md for the failover timeline and data flow.
"""

from __future__ import annotations

import os

HA_ENV = "GROVE_HA"


def ha_enabled() -> bool:
    """Read the kill switch per call (the GROVE_INFORMER idiom):
    flipping ``GROVE_HA=0`` mid-process restores pre-HA behavior —
    no fencing, no standby machinery — without rebuilding anything."""
    return os.environ.get(HA_ENV, "1") != "0"


def __getattr__(name: str):
    # Lazy submodule exports: grove_tpu.ha is imported by the store for
    # ha_enabled(), and eager election/standby imports from here would
    # cycle back through store/manager.
    if name in ("LeaderElector", "LeadershipState"):
        from grove_tpu.ha import election
        return getattr(election, name)
    if name in ("HotStandby", "StandbyServer"):
        from grove_tpu.ha import standby
        return getattr(standby, name)
    raise AttributeError(name)
