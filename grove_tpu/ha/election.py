"""Leader election with fencing epochs — campaign → renew → release.

Generalizes ``store/persist.py``'s flock + lease single-writer guard
(the reference's Lease-based election, ``operator/cmd/main.go`` →
manager.go:55-147, per proposal 0002) into an explicit leadership API:

- **campaign** — take (or confirm) the state dir's exclusive lock, then
  FENCE: bump the store's monotonic epoch (durable before returning)
  and stamp the manager's control-plane writers with it. From that
  moment any write still carrying an older epoch — a deposed leader's
  straggler reconcile, a zombie thread waking mid-write — is rejected
  by the store (``FencedError``), which is the guarantee SIGKILL
  fencing alone cannot give.
- **renew** — the lease heartbeat (persist.py stamps ``LEASE`` every
  TTL/5 from a daemon thread for the lock-hold lifetime); ``renew()``
  re-stamps once explicitly for callers that want a synchronous proof
  of liveness.
- **release** — demote the manager (park controllers, drop queued
  work, clear expectations) and optionally hand back the state-dir
  lock so a successor in the same process can acquire it.

``LeadershipState`` is the observable half: role, epoch, transitions,
and timestamps — served at ``/debug/leadership`` and rendered by
``grovectl leader-status``.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Any

from grove_tpu.ha import ha_enabled
from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.metrics import GLOBAL_METRICS

# store (weakly) -> the LeadershipState of the manager that runs it, so
# the in-process Client can serve debug_leadership like the other
# observatory twins (deploywatch.observer_for pattern). Registered at
# Manager.start(), so a constructed-but-unstarted Manager can't shadow
# the running one.
_LEADERSHIP: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def leadership_for(store) -> "LeadershipState | None":
    return _LEADERSHIP.get(store)


def register_leadership(store, state: "LeadershipState") -> None:
    _LEADERSHIP[store] = state


class LeadershipState:
    """This replica's view of who leads: role, fencing epoch, and the
    transition ledger. Thread-safe (the server reads while the manager
    transitions)."""

    def __init__(self, replica: str = ""):
        self.replica = replica or os.environ.get("GROVE_REPLICA", "r0")
        self._lock = threading.Lock()
        self.role = "leader"        # single-replica default: pre-HA shape
        self.epoch = 0
        self.leader_hint = ""       # where writes should go when standby
        self.transitions = 0
        self.changed_at = time.time()

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self.role == "leader"

    def note_promoted(self, epoch: int) -> None:
        with self._lock:
            was = self.role
            self.role = "leader"
            self.epoch = epoch
            self.leader_hint = ""
            if was != "leader":
                self.transitions += 1
            self.changed_at = time.time()
        GLOBAL_METRICS.set("grove_leader", 1.0, replica=self.replica)
        GLOBAL_METRICS.set("grove_leadership_epoch", float(epoch))
        if was != "leader":
            GLOBAL_METRICS.inc("grove_leadership_transitions_total",
                               direction="promoted")

    def note_demoted(self, leader_hint: str = "") -> None:
        with self._lock:
            was = self.role
            self.role = "standby"
            self.leader_hint = leader_hint
            if was == "leader":
                self.transitions += 1
            self.changed_at = time.time()
        GLOBAL_METRICS.set("grove_leader", 0.0, replica=self.replica)
        if was == "leader":
            GLOBAL_METRICS.inc("grove_leadership_transitions_total",
                               direction="demoted")

    def payload(self, store=None) -> dict:
        """The /debug/leadership document (one shape for the in-process
        twin, the wire endpoint, and the standby server)."""
        with self._lock:
            out = {
                "replica": self.replica,
                "role": self.role,
                "epoch": self.epoch,
                "leader_hint": self.leader_hint,
                "transitions": self.transitions,
                "since_s": round(time.time() - self.changed_at, 3),
                "ha_enabled": ha_enabled(),
            }
        if store is not None:
            # The store's epoch is the authority; a mismatch with the
            # replica's claimed epoch means this replica was fenced.
            out["store_epoch"] = store.fencing_epoch()
            out["fenced"] = (out["role"] == "leader"
                             and out["epoch"] < out["store_epoch"])
        return out


class LeaderElector:
    """Manager runnable driving campaign/renew/release for one manager.

    The flock acquisition itself rides the manager's Store construction
    (a persistent Store holds the state-dir lock before its first
    read); ``campaign()`` is the FENCING half — epoch bump + writer
    stamping + controller un-parking — and works for in-memory stores
    too (the epoch just isn't durable). As a runnable it campaigns at
    ``start()`` when the manager's config enables HA, so a 2-replica
    deployment is: leader serves, standby blocks in Store construction
    (takeover_wait) until the lease fences, then its elector campaigns.
    """

    def __init__(self, manager: Any, state_dir: str | None = None):
        self.manager = manager
        self.state_dir = state_dir
        self.log = get_logger("ha.elector")

    # -- campaign ---------------------------------------------------------

    def campaign(self) -> int:
        """Fence and lead: bump the store's epoch (durably, when
        persistent), stamp the manager's writers, un-park controllers,
        and record the transition. Returns the new epoch (0 with
        GROVE_HA=0 — the whole ceremony no-ops)."""
        if not ha_enabled():
            self.manager.leadership.note_promoted(
                self.manager.store.fencing_epoch())
            return 0
        epoch = self.manager.promote()
        self.log.info("campaign won: replica=%s epoch=%d",
                      self.manager.leadership.replica, epoch)
        return epoch

    def renew(self) -> None:
        """One synchronous lease re-stamp (the daemon heartbeat does
        this continuously; explicit renewal is for tests and probes)."""
        if self.state_dir is not None:
            from grove_tpu.store.persist import _stamp_lease
            _stamp_lease(self.state_dir)

    def release(self, hand_back_lock: bool = False) -> None:
        """Stand down: demote the manager (park + drop + clear); with
        ``hand_back_lock`` also release the state-dir flock so a
        successor in this process can acquire it."""
        self.manager.demote()
        if hand_back_lock and self.state_dir is not None:
            from grove_tpu.store.persist import release_state_lock
            release_state_lock(self.state_dir)

    # -- runnable ---------------------------------------------------------

    def start(self) -> None:
        self.campaign()

    def stop(self) -> None:
        pass    # leadership ends with the process (kernel frees the flock)
