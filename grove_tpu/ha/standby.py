"""Hot standby — a warm control-plane replica awaiting promotion.

The standby holds no store and runs no controllers; it maintains a
**mirror**: one merged object map over every kind, seeded by a full
relist against the leader's HTTP API and kept current by ONE
``resumable_watch_events`` stream (all kinds, all namespaces). Store
event seqs are consecutive (every allocated rv emits exactly one
event), so as long as nothing is filtered out of the stream the
mirror's cursor proves completeness: state-at-rv-R, byte-equivalent to
the leader's store at R. The watch loop tracks that **contiguity**; a
filtered event (e.g. Secrets hidden from a non-system token) or an
unhealed gap clears the flag and promotion falls back to the full
snapshot+WAL load rather than trusting an incomplete mirror.

``promote()`` is the failover critical path, and everything expensive
has been moved OFF it while the leader was still alive:

1. fence — take the state-dir flock (waits out the dead/wedged
   leader's lease; persist.py SIGKILLs a wedged holder), then bump the
   fencing epoch durably,
2. warm load — ``StatePersister.load_warm`` replays only the WAL
   records PAST the mirror's rv instead of decoding snapshot + full
   WAL (at a 300-pod deploy that is thousands of full-object JSON
   payloads skipped),
3. warm start — the promoted manager's controllers resync from
   informer caches over the loaded store; reconcile resumes where the
   dead leader stopped.

``StandbyServer`` is the replica's HTTP face while standing by: reads
served from the mirror, mutating verbs refused with 503 + a leader
hint that ``HttpClient`` / ``cli._http`` follow automatically.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any
from urllib.parse import parse_qs, urlparse

from grove_tpu.ha import ha_enabled
from grove_tpu.ha.election import LeadershipState
from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.metrics import GLOBAL_METRICS


class HotStandby:
    """Wire mirror + promotion for one standby replica."""

    def __init__(self, leader_url: str, state_dir: str | None = None,
                 token: str = "", replica: str = "standby",
                 poll_timeout: float = 5.0, ca_file: str = ""):
        from grove_tpu.store.httpclient import HttpClient
        self.leader_url = leader_url.rstrip("/")
        self.state_dir = state_dir
        # Generous timeout: a full-fleet relist during a churn storm on
        # a loaded leader can exceed the default 10s, and a failed seed
        # list marks the mirror incomplete (no warm promotion).
        self.http = HttpClient(self.leader_url, token=token,
                               ca_file=ca_file, timeout=60.0)
        # The standby watches THE leader it was pointed at; a 503
        # mid-watch means confusion worth surfacing, not following.
        self.http.follow_leader = False
        self.poll_timeout = poll_timeout
        self.leadership = LeadershipState(replica=replica)
        self.leadership.note_demoted(leader_hint=self.leader_url)
        self.log = get_logger("ha.standby")
        from grove_tpu.analysis import lockdep
        self._lock = lockdep.maybe_wrap(threading.Lock(), "standby")
        # (kind, ns, name) -> obj — the merged all-kind mirror.
        self._objects: dict[tuple[str, str, str], Any] = {}
        self.rv = 0
        # True while the event stream provably delivered EVERY seq
        # (consecutive seqs, no filtered events): the warm-load
        # precondition. Gaps that reseed via a full relist restore it.
        self.contiguous = False
        self.events_applied = 0
        self.relists = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ---- mirror maintenance ---------------------------------------------

    def start(self) -> None:
        self._seed()
        self._thread = threading.Thread(target=self._run,  # grovelint: disable=thread-join-in-stop -- mirrors the leader over a wire long-poll (up to poll_timeout); a promotion-path stop() cannot afford to wait that out, and the daemon thread only writes its own mirror
                                        name="ha-standby-watch",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _seed(self) -> int:
        """Full relist of every kind, rv-anchored BEFORE the lists (the
        WireSource discipline: writes landing between rv fetch and list
        are replayed by the resuming watch and absorbed by the
        per-object rv guard). Returns the seed rv."""
        from grove_tpu.manifest import KIND_REGISTRY
        rv = self.http.current_rv()
        objects: dict[tuple[str, str, str], Any] = {}
        complete = True
        for kind, cls in KIND_REGISTRY.items():
            try:
                for obj in self.http.list(cls, namespace=None):
                    objects[(kind, obj.meta.namespace, obj.meta.name)] = obj
            except Exception as e:  # noqa: BLE001 — e.g. Secrets 403
                # A kind we cannot list (censored for this token) can
                # never make the mirror complete: mark and keep seeding
                # the rest — the standby still serves what it CAN see,
                # and promotion falls back to the full load.
                self.log.warning("seed list of %s failed (%s); mirror "
                                 "marked non-contiguous — give the "
                                 "standby a system token for warm "
                                 "promotion", kind, e)
                complete = False
        with self._lock:
            self._objects = objects
            self.rv = rv
            self.contiguous = complete
        self.relists += 1
        GLOBAL_METRICS.set("grove_informer_cache_objects", len(objects),
                           kind="_standby_mirror")
        return rv

    def _run(self) -> None:
        from grove_tpu.store.httpclient import resumable_watch_events
        from grove_tpu.store.store import EventType

        def on_gap() -> int:
            # Missed events are unrecoverable: reseed the whole mirror
            # and resume at the relist's rv (no blind window) — the
            # reseed also RESTORES contiguity.
            return self._seed()

        for seq, etype, obj in resumable_watch_events(
                self.http, kinds=None, namespace=None,
                poll_timeout=self.poll_timeout, stop=self._stop,
                on_gap=on_gap,
                on_error=lambda e: self.log.warning(
                    "standby watch error: %s; retrying", e),
                since=self.rv):
            reseed = False
            with self._lock:
                if seq <= self.rv:
                    # Stale replay (the generator's cursor lags a
                    # mid-loop reseed that jumped the mirror ahead):
                    # the relist already reflects these events, and
                    # applying a stale DELETE would pop an object the
                    # relist legitimately re-seeded — the mirror would
                    # then claim rv=R while missing an object that
                    # exists at R, and warm promotion would lose it.
                    continue
                if seq > self.rv + 1 and self.contiguous:
                    # A seq was skipped: something filtered the stream
                    # (censored kind, proxy). The mirror can no longer
                    # prove completeness — but a full relist CAN
                    # restore it (the same medicine as a 410 gap), so
                    # heal instead of disabling warm promotion for the
                    # standby's whole life.
                    self.log.warning(
                        "standby stream skipped seqs %d..%d; reseeding "
                        "the mirror to restore contiguity",
                        self.rv + 1, seq - 1)
                    self.contiguous = False
                    reseed = True
                key = (obj.KIND, obj.meta.namespace, obj.meta.name)
                if etype == EventType.DELETED.value:
                    self._objects.pop(key, None)
                else:
                    old = self._objects.get(key)
                    if old is None or (old.meta.resource_version
                                       < obj.meta.resource_version):
                        self._objects[key] = obj
                if seq > self.rv:
                    self.rv = seq
                self.events_applied += 1
            if reseed:
                try:
                    # Relist at a fresh rv: in-flight events at or
                    # below it are absorbed by the per-object rv guard,
                    # and the cursor comparison resumes from the
                    # reseed's rv.
                    self._seed()
                except Exception as e:  # noqa: BLE001 — keep watching
                    self.log.warning("mirror reseed failed: %s; warm "
                                     "promotion stays disabled", e)

    def mirror_snapshot(self) -> tuple[dict, int, bool]:
        with self._lock:
            return dict(self._objects), self.rv, self.contiguous

    # ---- reads for the standby server -----------------------------------

    def list_objects(self, kind: str, namespace: str | None,
                     selector: dict[str, str] | None) -> list[Any]:
        from grove_tpu.store.store import matches_labels
        with self._lock:
            out = [o for (k, ns, _), o in self._objects.items()
                   if k == kind
                   and (namespace is None or ns == namespace)
                   and matches_labels(o, selector)]
        out.sort(key=lambda o: o.meta.name)
        return out

    def get_object(self, kind: str, name: str, namespace: str) -> Any | None:
        with self._lock:
            return self._objects.get((kind, namespace, name))

    # ---- promotion -------------------------------------------------------

    def promote(self, config: Any = None,
                takeover_wait: bool = True) -> Any:
        """Become the leader: fence, load (warm when provable), start a
        full cluster, and observe ``grove_failover_resume_seconds``.
        Blocks in Store construction until the old holder's flock is
        free or its lease fences it (persist.py). Returns the started
        ``Cluster``."""
        from grove_tpu.cluster import new_cluster
        from grove_tpu.runtime.errors import GroveError
        from grove_tpu.store.store import Store

        if self.state_dir is None:
            raise GroveError(
                "cannot promote a standby without a state_dir: the "
                "mirror is a cache, not the durable state — promotion "
                "must load (and flock) the leader's snapshot+WAL. "
                "State-dir-less standbys are read-replicas only.")
        t0 = time.perf_counter()
        self.stop()
        objects, rv, contiguous = self.mirror_snapshot()
        warm = None
        if contiguous and ha_enabled() and self.state_dir is not None:
            warm = (objects, rv)
        self.log.info("promoting: mirror at rv=%d (%d objects, "
                      "contiguous=%s) -> %s load", rv, len(objects),
                      contiguous, "warm" if warm else "full")
        t1 = time.perf_counter()
        store = Store(state_dir=self.state_dir,
                      takeover_wait=takeover_wait, warm=warm)
        t2 = time.perf_counter()
        cluster = new_cluster(store=store, config=config)
        mgr = cluster.manager
        mgr.leadership.replica = self.leadership.replica
        if ha_enabled():
            # Fence BEFORE controllers start: the epoch record is
            # durable in the WAL, so a zombie ex-leader's later appends
            # (stale epoch stamps) are dropped on any future load, and
            # its wire writes (stale X-Grove-Epoch) get 409s.
            mgr.promote()
        t3 = time.perf_counter()
        cluster.start()
        resumed = time.perf_counter() - t0
        GLOBAL_METRICS.observe("grove_failover_resume_seconds", resumed)
        self.leadership = mgr.leadership
        mode = (store._persister.last_load.get("mode", "?")
                if store._persister else "none")
        # Phase split for the failover bench: where promotion wall time
        # went (the load phase is what the warm path optimizes).
        self.last_promotion = {
            "total_s": round(resumed, 4),
            "load_s": round(t2 - t1, 4),
            "construct_s": round(t3 - t2, 4),
            "start_s": round(resumed - (t3 - t0), 4),
            "mode": mode,
        }
        self.log.info("promoted in %.3fs (load=%s %.3fs, epoch=%d)",
                      resumed, mode, t2 - t1, store.fencing_epoch())
        return cluster


class StandbyServer:
    """The standby's HTTP face: reads from the mirror, 503 + leader
    hint on anything mutating. Deliberately slim — no watch (the
    standby has no event ring), no debug observatories (no manager);
    Secrets are never served (the mirror bypasses the store's
    per-actor authorization, so the conservative rule is total)."""

    def __init__(self, standby: HotStandby, host: str = "127.0.0.1",
                 port: int = 0):
        self.standby = standby
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None

    def start(self) -> None:
        standby = self.standby

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, payload) -> None:
                body = json.dumps(payload, indent=2).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _refuse_write(self) -> None:
                self._send(503, {
                    "error": "this replica is a hot standby; writes "
                             "must go to the leader",
                    "leader": standby.leader_url})

            def do_GET(self):
                from grove_tpu.api.serde import to_dict
                url = urlparse(self.path)
                parts = [p for p in url.path.split("/") if p]
                if url.path == "/healthz":
                    self._send(200, {"started": True, "role": "standby",
                                     "mirror_rv": standby.rv,
                                     "objects": len(standby._objects)})
                    return
                if url.path == "/debug/leadership":
                    self._send(200, standby.leadership.payload())
                    return
                if len(parts) in (2, 3) and parts[0] == "api":
                    kind = parts[1]
                    if kind == "Secret":
                        self._send(403, {"error": "Secrets are not "
                                         "served from a standby"})
                        return
                    q = parse_qs(url.query)
                    ns = q.get("namespace", ["default"])[0]
                    if len(parts) == 3:
                        obj = standby.get_object(kind, parts[2], ns)
                        if obj is None:
                            self._send(404, {"error":
                                             f"{kind} {ns}/{parts[2]} "
                                             "not found (standby mirror)"})
                        else:
                            self._send(200, to_dict(obj))
                        return
                    selector = {k[2:]: v[0] for k, v in q.items()
                                if k.startswith("l.")}
                    objs = standby.list_objects(
                        kind, None if ns == "*" else ns, selector or None)
                    self._send(200, [to_dict(o) for o in objs])
                    return
                if url.path == "/watch":
                    # No event ring here; the hint sends watchers to
                    # the leader like any writer.
                    self._refuse_write()
                    return
                self._send(404, {"error": "not found (standby serves "
                                 "/api reads, /healthz, "
                                 "/debug/leadership)"})

            def do_POST(self):
                self._refuse_write()

            do_PUT = do_POST
            do_PATCH = do_POST
            do_DELETE = do_POST

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="standby-server",
            daemon=True)
        self._serve_thread.start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        # serve_forever returns at shutdown(); join so a stopped
        # standby server provably serves nothing (grovelint
        # thread-join-in-stop).
        if getattr(self, "_serve_thread", None) is not None:
            self._serve_thread.join(timeout=2.0)
            self._serve_thread = None
