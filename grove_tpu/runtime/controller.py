"""Controller runtime: rate-limited workqueue + watch-driven reconcilers.

Role parity with controller-runtime as used by the reference (SURVEY.md
§1 L2): each controller owns a dedup-ing delay queue fed by store watch
events through mapper functions; N worker threads pop requests and call
the reconcile function; failures requeue with exponential backoff; a
StepResult can ask for a delayed requeue.

Read path: the ``client`` a controller is registered with is the
manager's ``CachedClient`` (runtime/informer.py), so both the startup
``_resync`` list and every list a reconciler issues inside ``_process``
are indexed lookups over the shared per-kind informer caches instead of
store scans; ``GROVE_INFORMER=0`` restores direct reads. Listed objects
are shared cache state — reconcilers clone before mutating them.
"""

from __future__ import annotations

import collections
import heapq
import itertools
import threading
import time
from typing import Any, Callable, NamedTuple

from grove_tpu.runtime.flow import StepResult
from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.metrics import GLOBAL_METRICS
from grove_tpu.api.meta import trace_id_of
from grove_tpu.runtime import sweepobs
from grove_tpu.runtime.trace import GLOBAL_TRACER
from grove_tpu.store import writeobs
from grove_tpu.store.store import Event
from grove_tpu.store.client import Client


class Request(NamedTuple):
    namespace: str
    name: str

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


def owner_requests(event: Event, kind: str) -> list[Request]:
    """Map an event to requests for its controller owner of ``kind``."""
    obj = event.obj
    return [Request(obj.meta.namespace, ref.name)
            for ref in obj.meta.owner_references
            if ref.kind == kind and ref.controller]


def self_requests(event: Event) -> list[Request]:
    return [Request(event.obj.meta.namespace, event.obj.meta.name)]


class _DelayQueue:
    """Dedup-ing delay queue: an item re-added while pending is not
    duplicated; an item re-added while being processed is re-queued after
    processing (the k8s workqueue 'dirty' semantics)."""

    def __init__(self, name: str = "") -> None:
        self.name = name  # metric label (owning controller)
        self._lock = threading.Condition()
        self._heap: list[tuple[float, int, Request]] = []
        self._seq = itertools.count()
        self._pending: set[Request] = set()
        self._processing: set[Request] = set()
        self._dirty: set[Request] = set()
        # Earliest READY time per pending request: queue-wait is
        # measured from readiness (a backoff delay is intentional
        # latency, not queue congestion) to worker pickup.
        self._ready: dict[Request, float] = {}
        # Lifecycle-trace hint per request: the trace id of the event
        # object that (most recently) enqueued it. Dedup keeps the
        # latest hint; _process pops it to bind the reconcile span to
        # the trace that woke the request.
        self._trace: dict[Request, str] = {}
        # Trigger-cause hint, riding exactly like the trace hint: what
        # woke this request (watch:<Kind>, resync, requeue, backoff,
        # panic) — the sweep observatory's cause label. Dedup keeps the
        # latest cause; a dirty re-add inherits the cause of the event
        # that arrived mid-processing (add() records it before the
        # dirty check).
        self._cause: dict[Request, str] = {}
        self._shutdown = False

    def add(self, req: Request, delay: float = 0.0,
            trace_id: str = "", cause: str = "") -> None:
        with self._lock:
            if self._shutdown:
                return
            if trace_id:
                self._trace[req] = trace_id
            if cause:
                self._cause[req] = cause
            if req in self._processing:
                self._dirty.add(req)
                return
            # Always push: a watch event (delay=0) must be able to
            # accelerate a request sitting out a backoff window. The
            # _pending set makes delivery once-only — after the earliest
            # entry pops, stale heap entries are skipped by get().
            self._pending.add(req)
            ready = time.time() + delay
            prev = self._ready.get(req)
            if prev is None or ready < prev:
                self._ready[req] = ready
            heapq.heappush(self._heap, (ready, next(self._seq), req))
            self._lock.notify()

    def get(self, timeout: float = 0.2) -> Request | None:
        req, queued_for = None, 0.0
        with self._lock:
            deadline = time.time() + timeout
            while req is None:
                if self._shutdown:
                    return None
                now = time.time()
                while self._heap and self._heap[0][2] not in self._pending:
                    heapq.heappop(self._heap)  # stale entry (already popped)
                if self._heap and self._heap[0][0] <= now:
                    _, _, req = heapq.heappop(self._heap)
                    self._pending.discard(req)
                    self._processing.add(req)
                    queued_for = max(0.0, now - self._ready.pop(req, now))
                    break
                wait = min(
                    self._heap[0][0] - now if self._heap else timeout,
                    deadline - now)
                if wait <= 0:
                    return None
                self._lock.wait(wait)
        # Observed OUTSIDE the queue Condition: the metrics hub has one
        # global lock, and render() (every /metrics scrape) holds it
        # while formatting — observing under the Condition would stall
        # every enqueue on this queue behind each scrape.
        GLOBAL_METRICS.observe("grove_workqueue_wait_seconds", queued_for,
                               controller=self.name)
        return req

    def pop_trace(self, req: Request) -> str:
        """Take the trace hint for a request this worker just popped
        ('' when it arrived untraced). Safe without further
        coordination: dedup guarantees one worker holds ``req``."""
        return self.pop_hints(req)[0]

    def pop_hints(self, req: Request) -> tuple[str, str]:
        """(trace_id, cause) for a just-popped request, both '' when
        absent — one lock round trip for the pair."""
        with self._lock:
            return self._trace.pop(req, ""), self._cause.pop(req, "")

    def done(self, req: Request) -> None:
        with self._lock:
            self._processing.discard(req)
            if req in self._dirty:
                self._dirty.discard(req)
                self._pending.add(req)
                now = time.time()
                self._ready[req] = now
                heapq.heappush(self._heap, (now, next(self._seq), req))
                self._lock.notify()

    def drain(self) -> int:
        """Drop every queued (not-yet-picked-up) request — demotion
        hygiene: a deposed leader's backlog was computed under a view
        a new leader is already rewriting, and replaying it on
        re-promotion would race the fresh resync. In-flight requests
        finish (their writes are fenced); their dirty re-adds are
        dropped with the rest. Returns the number dropped."""
        with self._lock:
            n = len(self._pending) + len(self._dirty)
            self._heap.clear()
            self._pending.clear()
            self._dirty.clear()
            self._ready.clear()
            self._trace.clear()
            self._cause.clear()
            return n

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._lock.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending) + len(self._processing)


class Controller:
    """A named reconciler with its own queue, workers, and watches."""

    def __init__(self, name: str, client: Client,
                 reconcile: Callable[[Request], StepResult | None],
                 workers: int = 2,
                 backoff_base: float = 0.05,
                 backoff_max: float = 5.0):
        self.name = name
        self.client = client
        self.reconcile = reconcile
        self.workers = workers
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.queue = _DelayQueue(name)
        self.log = get_logger(f"controller.{name}")
        self._failures: dict[Request, int] = {}
        self._watch_specs: list[tuple[list[str] | None,
                                      Callable[[Event], list[Request]],
                                      dict[str, str] | None]] = []
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        # Leadership parking (grove_tpu/ha): a parked controller's
        # watches keep flowing into the informer caches, but nothing
        # reaches the queue and workers drop anything already popped —
        # a standby/demoted replica observes without reconciling.
        self._parked = False
        # Demotion hook (Manager.demote): clears reconciler-owned state
        # that must not survive a leadership gap (ExpectationsStore —
        # stale expectations on re-promotion are the SURVEY §7
        # duplicate-pod hazard). Set by controller registration.
        self.on_park: Callable[[], Any] | None = None
        # Sweep observatory (runtime/sweepobs.py), wired by
        # Manager.add_controller; None for unmanaged controllers
        # (benches construct their own observer or run unattributed).
        self.sweep_observer: Any = None
        self.reconcile_count = 0
        self.error_count = 0
        # Per-request-key reconcile totals (under _count_lock: worker
        # threads race on +=). The scale runner's steady-state phase
        # asserts per-clique deltas from here — an aggregate count can't
        # distinguish "coalescing works" from "fan-out lost" (a floor
        # met with zero margin looks identical either way).
        self.key_counts: collections.Counter = collections.Counter()
        self._count_lock = threading.Lock()
        # Recent reconcile wall times (ring, thread-safe via GIL append):
        # the steady-state scale phase reports p50/p95 from here, the
        # analog of the reference profiling its no-op reconcile cost
        # (scale_test.go:216-240).
        self.durations: "collections.deque[float]" = \
            collections.deque(maxlen=4096)

    # ---- wiring ----

    def snapshot_key_counts(self) -> dict[str, int]:
        """Copy of per-key reconcile totals under the writers' lock (an
        unlocked dict() can race a first-seen-key insert mid-iteration)."""
        with self._count_lock:
            return dict(self.key_counts)

    def watches(self, kinds: list[str] | None,
                mapper: Callable[[Event], list[Request]],
                selector: dict[str, str] | None = None) -> "Controller":
        self._watch_specs.append((kinds, mapper, selector))
        return self

    def enqueue(self, req: Request, delay: float = 0.0,
                trace_id: str = "", cause: str = "") -> None:
        if self._parked:
            return
        self.queue.add(req, delay, trace_id=trace_id, cause=cause)

    # ---- leadership parking (grove_tpu/ha) ----

    def park(self) -> int:
        """Stop reconciling (demotion/standby): drop all queued work
        and gate new enqueues. Watches keep running — cache freshness
        is leadership-independent. Returns dropped-item count, and runs
        the registered on_park hook (expectations clear)."""
        self._parked = True
        dropped = self.queue.drain()
        # Gauge hygiene: the drain above empties the queue, but the
        # depth gauge is only re-sampled by Manager.metrics_text — a
        # standby scraped through the raw hub between demote and the
        # next metrics_text would read the pre-demote depth as live
        # load. Zero it (and this controller's sweep gauges) NOW.
        GLOBAL_METRICS.set("grove_workqueue_depth", 0.0,
                           controller=self.name)
        if self.sweep_observer is not None:
            self.sweep_observer.on_park(self.name)
        if self.on_park is not None:
            try:
                self.on_park()
            except Exception:  # noqa: BLE001 — hygiene must not block
                self.log.exception("on_park hook panicked")
        return dropped

    def unpark(self) -> None:
        """Resume reconciling (promotion): re-open the queue, then
        resync every watch so the backlog rebuilds from LIVE state —
        the warm-start reconcile (informer caches are already current;
        the resync is index reads, not store scans)."""
        if not self._parked:
            return
        self._parked = False
        if self.sweep_observer is not None:
            self.sweep_observer.on_unpark(self.name)
        for kinds, mapper, selector in self._watch_specs:
            self._resync(kinds, mapper, selector)

    # ---- lifecycle ----

    def start(self) -> None:
        for kinds, mapper, selector in self._watch_specs:
            watcher = self.client.watch(kinds, selector)
            # Initial resync (the informer initial-LIST): objects created
            # before start would otherwise never be reconciled.
            self._resync(kinds, mapper, selector)
            t = threading.Thread(target=self._dispatch, args=(watcher, mapper),
                                 name=f"{self.name}-watch", daemon=True)
            t.start()
            self._threads.append(t)
        for i in range(self.workers):
            t = threading.Thread(target=self._worker,
                                 name=f"{self.name}-worker-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def request_stop(self) -> None:
        """Signal only (idempotent): flips the stop flag and unblocks
        workers. The manager signals EVERY controller before joining
        any (Manager.stop), so all dispatch threads run out their
        0.2s poll concurrently instead of serially per controller."""
        self._stop.set()
        self.queue.shutdown()

    def stop(self) -> None:
        self.request_stop()
        # Bounded join of watch dispatchers (0.2s poll) and workers
        # (unblocked by the queue shutdown above): a worker finishing a
        # reconcile after stop() returns writes into a store the
        # caller already considers quiesced (grovelint
        # thread-join-in-stop). Self-join guard: a reconcile that
        # stops its own manager must not deadlock on itself.
        me = threading.current_thread()
        for t in self._threads:
            if t is not me:
                t.join(timeout=2.0)
        self._threads = [t for t in self._threads if t.is_alive()]

    def _resync(self, kinds, mapper, selector) -> None:
        from grove_tpu.manifest import KIND_REGISTRY
        from grove_tpu.store.store import Event, EventType
        for kind in kinds or []:
            kind_cls = KIND_REGISTRY.get(kind)
            if kind_cls is None:
                continue
            try:
                # Through the shared informer cache when the client is
                # the manager's CachedClient: the warm-up list seeds the
                # kind's informer once; later resyncs are index reads.
                objs = self.client.list(kind_cls, namespace=None,
                                        selector=selector)
            except Exception:  # noqa: BLE001 - best-effort warm-up
                continue
            for obj in objs:
                try:
                    tid = trace_id_of(obj)
                    for req in mapper(Event(EventType.ADDED, obj)):
                        self.enqueue(req, trace_id=tid, cause="resync")
                except Exception:  # noqa: BLE001
                    self.log.exception("resync mapper panic")

    def _dispatch(self, watcher, mapper) -> None:
        while not self._stop.is_set():
            event = watcher.poll(timeout=0.2)
            if event is None:
                continue
            try:
                # Trace propagation through the workqueue: the event
                # object's trace id rides along as a hint so the
                # reconcile it triggers lands in the same trace; the
                # cause hint names the waking event's kind.
                tid = trace_id_of(event.obj)
                cause = f"watch:{event.obj.KIND}"
                for req in mapper(event):
                    self.enqueue(req, trace_id=tid, cause=cause)
            except Exception:  # noqa: BLE001
                self.log.exception("watch mapper panic (event dropped)")

    def _worker(self) -> None:
        while not self._stop.is_set():
            req = self.queue.get(timeout=0.2)
            if req is None:
                continue
            if self._parked:
                # Popped between drain and the gate closing (or while
                # parked): a standby must not reconcile.
                self.queue.done(req)
                continue
            t0 = time.perf_counter()
            try:
                self._process(req)
            finally:
                self.queue.done(req)
                # Work duration, pickup → done (the workqueue_work_
                # duration_seconds analog): with the queue-wait
                # histogram this is the congestion split the deploy
                # observatory reports — time spent waiting for a worker
                # vs time spent being worked on.
                GLOBAL_METRICS.observe("grove_workqueue_work_seconds",
                                       time.perf_counter() - t0,
                                       controller=self.name)

    def _process(self, req: Request) -> None:
        with self._count_lock:
            self.reconcile_count += 1
            self.key_counts[req.key] += 1
        GLOBAL_METRICS.inc("grove_reconcile_total", controller=self.name)
        # Reconcile span: bound to the trace that enqueued this request
        # (no-op for untraced requests). The span context is ambient
        # for the reconcile body, so objects it creates and nested
        # spans it opens land in the same trace.
        trace_hint, cause_hint = self.queue.pop_hints(req)
        t0 = time.perf_counter()
        # Writer attribution for store write telemetry: every write the
        # reconcile body issues — however deep, including fan-out
        # through helpers on this thread — is labeled with this
        # controller's name (grove_store_writes_total{writer=...}).
        writer_token = writeobs.set_writer(self.name)
        try:
            # Sweep attribution (runtime/sweepobs.py): a bare yield
            # when GROVE_SWEEP_OBS=0 or the controller is unmanaged —
            # the prior path, pinned by the overhead test.
            with sweepobs.maybe_record(self.sweep_observer, self.name,
                                       cause_hint, req.key), \
                 GLOBAL_TRACER.span(f"reconcile.{self.name}",
                                    trace_id=trace_hint or None,
                                    attrs={"key": req.key}) as span:
                try:
                    try:
                        result = self.reconcile(req) or \
                            StepResult.finished()
                    finally:
                        dt = time.perf_counter() - t0
                        self.durations.append(dt)
                        GLOBAL_METRICS.observe(
                            "grove_reconcile_duration_seconds",
                            dt, controller=self.name)
                except Exception as e:  # noqa: BLE001 - panic barrier
                    self.error_count += 1
                    span.set_error(e)
                    self.log.warning("reconcile %s panicked: %s", req.key,
                                     e, exc_info=True)
                    self._requeue_with_backoff(req, trace_id=trace_hint,
                                               reason="panic")
                    return
                if result.error is not None:
                    self.error_count += 1
                    span.set_error(result.error)
                    GLOBAL_METRICS.inc("grove_reconcile_errors_total",
                                       controller=self.name)
                    self.log.debug("reconcile %s error: %s", req.key,
                                   result.error)
                    self._requeue_with_backoff(req, result.requeue_after,
                                               trace_id=trace_hint)
                    return
                self._failures.pop(req, None)
                if result.requeue_after is not None:
                    GLOBAL_METRICS.inc("grove_reconcile_requeues_total",
                                       controller=self.name,
                                       reason="requeue_after")
                    self.enqueue(req, result.requeue_after,
                                 trace_id=trace_hint, cause="requeue")
        finally:
            writeobs.reset_writer(writer_token)

    def _requeue_with_backoff(self, req: Request,
                              override: float | None = None,
                              trace_id: str = "",
                              reason: str | None = None) -> None:
        # The trace hint rides through the retry: error-and-backoff
        # reconciles are exactly the ones a slow-bring-up trace must
        # show, not lose.
        n = self._failures.get(req, 0) + 1
        self._failures[req] = n
        delay = override if override is not None else min(
            self.backoff_base * (2 ** (n - 1)), self.backoff_max)
        why = reason or ("requeue_after" if override is not None
                         else "backoff")
        GLOBAL_METRICS.inc(
            "grove_reconcile_requeues_total", controller=self.name,
            reason=why)
        self.enqueue(req, delay, trace_id=trace_id,
                     cause="requeue" if why == "requeue_after" else why)
