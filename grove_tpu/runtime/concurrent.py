"""Bounded / slow-start concurrent task running.

Role parity with reference internal/utils/concurrent.go:70-104
(RunConcurrently[WithSlowStart|WithBounds]): component sync fans out many
store mutations; batches double in size (1, 2, 4, ...) so one systemic
failure surfaces after O(log n) attempts instead of n.

Tasks run on ONE process-wide executor instead of a fresh
ThreadPoolExecutor per call: reconcile-path profiling showed executor
construction/teardown (thread spawn + join per batch) dominating pod
fan-out at fleet scale — hundreds of OS threads created and destroyed
per deploy for tasks that are store mutations serialized by the store
lock anyway. The pool is lazy, daemon-threaded, and bounded; a single
task (or a task already running ON the pool — nesting must never wait
on its own workers) runs inline.
"""

from __future__ import annotations

import contextvars
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence

# Sized for the worst in-tree fan-in: one cluster runs ~12 reconcile
# workers that may each park a pod-creation batch here; tasks are
# GIL-bound store mutations, so extra threads cost memory, not cores.
_POOL_WORKERS = 32
_pool_lock = threading.Lock()
_pool: ThreadPoolExecutor | None = None
_in_pool = threading.local()


def _shared_pool() -> ThreadPoolExecutor:
    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = ThreadPoolExecutor(max_workers=_POOL_WORKERS,
                                       thread_name_prefix="grove-sync")
        return _pool


def run_concurrently(tasks: Sequence[Callable[[], None]],
                     max_workers: int = 8) -> list[Exception]:
    """Run all tasks; return the list of raised exceptions (empty == ok).

    ``max_workers`` is kept for signature parity; concurrency is bounded
    by the shared pool (``_POOL_WORKERS``) across ALL callers, which is
    the global bound that matters.
    """
    errors: list[Exception] = []
    if not tasks:
        return errors
    if len(tasks) <= 2 or getattr(_in_pool, "active", False):
        # Inline: a 1-2 task fan-out (component-sync pairs, the first
        # slow-start batches) gains nothing from a pool hop — the store
        # lock serializes the mutations anyway — and a task already on
        # the pool must not block waiting for pool capacity it may
        # itself be occupying (the nested-submit deadlock).
        for t in tasks:
            try:
                t()
            except Exception as e:  # noqa: BLE001 - collected, not swallowed
                errors.append(e)
        return errors

    def wrapped(task: Callable[[], None],
                ctx: contextvars.Context) -> None:
        _in_pool.active = True
        try:
            # Run under the SUBMITTER's contextvars: pool threads have
            # their own (empty) context, which would silently drop
            # context-scoped attribution — e.g. the store write
            # telemetry's writer label set per reconcile
            # (store/writeobs.py) must follow a pod-creation burst onto
            # these threads, or the deploy's dominant write class reads
            # writer="direct".
            ctx.run(task)
        finally:
            _in_pool.active = False

    futures = [_shared_pool().submit(wrapped, t,
                                     contextvars.copy_context())
               for t in tasks]
    for f in futures:
        try:
            f.result()
        except Exception as e:  # noqa: BLE001 - collected, not swallowed
            errors.append(e)
    return errors


def run_with_slow_start(tasks: Sequence[Callable[[], None]],
                        initial_batch: int = 1,
                        max_workers: int = 8) -> tuple[int, list[Exception]]:
    """Run in doubling batches; stop at the first batch with any failure.

    Returns (successes, errors). Mirrors the kube slow-start pattern used
    for pod creation bursts.
    """
    done = 0
    batch = max(1, initial_batch)
    remaining = list(tasks)
    while remaining:
        current, remaining = remaining[:batch], remaining[batch:]
        errors = run_concurrently(current, max_workers=max_workers)
        done += len(current) - len(errors)
        if errors:
            return done, errors
        batch *= 2
    return done, []
