"""Bounded / slow-start concurrent task running.

Role parity with reference internal/utils/concurrent.go:70-104
(RunConcurrently[WithSlowStart|WithBounds]): component sync fans out many
store mutations; batches double in size (1, 2, 4, ...) so one systemic
failure surfaces after O(log n) attempts instead of n.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence


def run_concurrently(tasks: Sequence[Callable[[], None]],
                     max_workers: int = 8) -> list[Exception]:
    """Run all tasks; return the list of raised exceptions (empty == ok)."""
    errors: list[Exception] = []
    if not tasks:
        return errors
    with ThreadPoolExecutor(max_workers=min(max_workers, len(tasks))) as ex:
        futures = [ex.submit(t) for t in tasks]
        for f in futures:
            try:
                f.result()
            except Exception as e:  # noqa: BLE001 - collected, not swallowed
                errors.append(e)
    return errors


def run_with_slow_start(tasks: Sequence[Callable[[], None]],
                        initial_batch: int = 1,
                        max_workers: int = 8) -> tuple[int, list[Exception]]:
    """Run in doubling batches; stop at the first batch with any failure.

    Returns (successes, errors). Mirrors the kube slow-start pattern used
    for pod creation bursts.
    """
    done = 0
    batch = max(1, initial_batch)
    remaining = list(tasks)
    while remaining:
        current, remaining = remaining[:batch], remaining[batch:]
        errors = run_concurrently(current, max_workers=max_workers)
        done += len(current) - len(errors)
        if errors:
            return done, errors
        batch *= 2
    return done, []
