"""Structured errors with codes, mapped into status.last_errors.

Role parity with reference internal/errors/errors.go:36-92 (GroveError
{code, operation, message}) plus the apiserver error taxonomy the store
needs (NotFound / Conflict / AlreadyExists), which the reference gets from
k8s.io/apimachinery.
"""

from __future__ import annotations

import time


class GroveError(Exception):
    code = "ERR_UNKNOWN"

    def __init__(self, message: str, operation: str = "", code: str | None = None):
        super().__init__(message)
        self.message = message
        self.operation = operation
        if code is not None:
            self.code = code
        self.observed_at = time.time()

    def __str__(self) -> str:  # pragma: no cover - repr plumbing
        op = f" op={self.operation}" if self.operation else ""
        return f"[{self.code}{op}] {self.message}"


class NotFoundError(GroveError):
    code = "ERR_NOT_FOUND"


class AlreadyExistsError(GroveError):
    code = "ERR_ALREADY_EXISTS"


class ConflictError(GroveError):
    """Optimistic-concurrency conflict (stale resource_version)."""

    code = "ERR_CONFLICT"


class FencedError(ConflictError):
    """Write rejected by the leadership fence: the writer's epoch is
    older than the store's — a deposed leader (or its straggler
    threads) tried to write after a newer leader fenced the store.
    A ConflictError subclass so wire mapping (409) and existing
    conflict handling treat it as a terminal staleness signal, but
    unlike an rv conflict there is no point re-reading and retrying:
    the epoch only moves forward."""

    code = "ERR_FENCED"


class ValidationError(GroveError):
    code = "ERR_VALIDATION"


class ForbiddenError(GroveError):
    code = "ERR_FORBIDDEN"


def is_retriable(err: Exception) -> bool:
    """Conflicts and transient store errors are retried by requeueing."""
    return isinstance(err, ConflictError)
