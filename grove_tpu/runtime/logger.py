"""Structured logging (reference internal/logger/logger.go analog)."""

from __future__ import annotations

import json
import logging
import sys


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "ts": self.formatTime(record),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        extra = getattr(record, "fields", None)
        if extra:
            payload.update(extra)
        return json.dumps(payload)


def setup_logging(level: str = "info", fmt: str = "text") -> None:
    """Idempotent-but-live configuration: a repeat call (a second
    Manager in one process, a config reload) updates the level and
    formatter on the existing handlers instead of silently keeping the
    first call's configuration — only handler *creation* is once-only."""
    root = logging.getLogger("grove")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    if fmt == "json":
        formatter: logging.Formatter = _JsonFormatter()
    else:
        formatter = logging.Formatter(
            "%(asctime)s %(levelname)-7s %(name)s %(message)s")
    if root.handlers:
        for handler in root.handlers:
            handler.setFormatter(formatter)
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(formatter)
    root.addHandler(handler)
    root.propagate = False


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"grove.{name}")
