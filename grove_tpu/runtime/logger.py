"""Structured logging (reference internal/logger/logger.go analog)."""

from __future__ import annotations

import json
import logging
import sys


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "ts": self.formatTime(record),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        extra = getattr(record, "fields", None)
        if extra:
            payload.update(extra)
        return json.dumps(payload)


def setup_logging(level: str = "info", fmt: str = "text") -> None:
    root = logging.getLogger("grove")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    if root.handlers:
        return
    handler = logging.StreamHandler(sys.stderr)
    if fmt == "json":
        handler.setFormatter(_JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)-7s %(name)s %(message)s"))
    root.addHandler(handler)
    root.propagate = False


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"grove.{name}")
