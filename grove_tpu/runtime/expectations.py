"""Create/delete expectations — the informer-staleness barrier.

Role parity with reference internal/expect/expectations.go:18-92: after a
reconciler issues creates/deletes, the watch cache may not reflect them on
the next sync; acting on the stale view would double-create or over-delete.
The reconciler records expected UIDs here and skips mutating sync passes
until observed events have cleared them (or they time out).

Observability (SURVEY.md §7 names the double-create hazard; the chaos
harness checks its *consequences*, this surfaces the *cause*): the store
exports ``grove_expectations_pending{controller}`` — outstanding
unobserved create/delete UIDs — and counts TTL expiries in
``grove_expectations_expired_total{controller}``. An expectation that
expires instead of being observed means a watch event was lost (or the
TTL is too tight for the fleet's event lag); before these, a leaked
expectation was invisible until the chaos checker tripped on duplicate
pods. The ``on_expired`` callback lets the owning reconciler attach a
Warning event to the object whose sync window leaked.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class _Expectation:
    __slots__ = ("creates", "deletes", "stamp")

    def __init__(self) -> None:
        self.creates: set[str] = set()
        self.deletes: set[str] = set()
        self.stamp = time.time()


class ExpectationsStore:
    def __init__(self, ttl_seconds: float = 30.0, controller: str = "",
                 on_expired: Optional[Callable[[str, int, int], None]] = None):
        """``controller`` labels the pending gauge / expiry counter;
        ``on_expired(key, leaked_creates, leaked_deletes)`` fires (outside
        the lock) when an expectation times out with UIDs still
        unobserved — the hook for a Warning event on the object."""
        self._lock = threading.Lock()
        self._by_key: dict[str, _Expectation] = {}
        self._ttl = ttl_seconds
        self.controller = controller
        self.on_expired = on_expired

    def _export_pending_locked(self) -> None:
        if not self.controller:
            return
        from grove_tpu.runtime.metrics import GLOBAL_METRICS
        pending = sum(len(e.creates) + len(e.deletes)
                      for e in self._by_key.values())
        GLOBAL_METRICS.set("grove_expectations_pending", float(pending),
                           controller=self.controller)

    def expect_creates(self, key: str, uids: list[str]) -> None:
        with self._lock:
            exp = self._by_key.setdefault(key, _Expectation())
            exp.creates.update(uids)
            exp.stamp = time.time()
            self._export_pending_locked()

    def expect_deletes(self, key: str, uids: list[str]) -> None:
        with self._lock:
            exp = self._by_key.setdefault(key, _Expectation())
            exp.deletes.update(uids)
            exp.stamp = time.time()
            self._export_pending_locked()

    def observe_create(self, key: str, uid: str) -> None:
        with self._lock:
            exp = self._by_key.get(key)
            if exp:
                exp.creates.discard(uid)
                self._export_pending_locked()

    def observe_delete(self, key: str, uid: str) -> None:
        with self._lock:
            exp = self._by_key.get(key)
            if exp:
                exp.deletes.discard(uid)
                self._export_pending_locked()

    def satisfied(self, key: str) -> bool:
        """True when all expected events have been observed (or expired —
        expired expectations are dropped so a lost event can't wedge the
        controller forever; the next sync recomputes from live state).
        Expiry with UIDs still outstanding is the leak signal: counted,
        and reported through ``on_expired``."""
        leaked: tuple[int, int] | None = None
        with self._lock:
            exp = self._by_key.get(key)
            if exp is None:
                return True
            if not exp.creates and not exp.deletes:
                del self._by_key[key]
                self._export_pending_locked()
                return True
            if time.time() - exp.stamp > self._ttl:
                leaked = (len(exp.creates), len(exp.deletes))
                del self._by_key[key]
                self._export_pending_locked()
                if self.controller:
                    from grove_tpu.runtime.metrics import GLOBAL_METRICS
                    GLOBAL_METRICS.inc("grove_expectations_expired_total",
                                       controller=self.controller)
        if leaked is not None:
            if self.on_expired is not None:
                try:
                    self.on_expired(key, *leaked)
                except Exception:  # noqa: BLE001 — observability must
                    pass           # never break the sync path
            return True
        return False

    def forget(self, key: str) -> None:
        with self._lock:
            self._by_key.pop(key, None)
            self._export_pending_locked()

    def clear(self) -> int:
        """Drop EVERY expectation — demotion hygiene (grove_tpu/ha).
        Expectations are watch-delivery IOUs against THIS replica's
        informer feed; across a leadership gap the events they await
        may have been consumed by another leader entirely, and a
        re-promoted replica acting on the stale ledger would skip (or
        double-run) mutating sync passes — the SURVEY §7 duplicate-pod
        hazard verbatim. The next sync recomputes from live state.
        Returns the number of keys dropped."""
        with self._lock:
            n = len(self._by_key)
            self._by_key.clear()
            self._export_pending_locked()
        return n
