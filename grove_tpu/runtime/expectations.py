"""Create/delete expectations — the informer-staleness barrier.

Role parity with reference internal/expect/expectations.go:18-92: after a
reconciler issues creates/deletes, the watch cache may not reflect them on
the next sync; acting on the stale view would double-create or over-delete.
The reconciler records expected UIDs here and skips mutating sync passes
until observed events have cleared them (or they time out).
"""

from __future__ import annotations

import threading
import time


class _Expectation:
    __slots__ = ("creates", "deletes", "stamp")

    def __init__(self) -> None:
        self.creates: set[str] = set()
        self.deletes: set[str] = set()
        self.stamp = time.time()


class ExpectationsStore:
    def __init__(self, ttl_seconds: float = 30.0):
        self._lock = threading.Lock()
        self._by_key: dict[str, _Expectation] = {}
        self._ttl = ttl_seconds

    def expect_creates(self, key: str, uids: list[str]) -> None:
        with self._lock:
            exp = self._by_key.setdefault(key, _Expectation())
            exp.creates.update(uids)
            exp.stamp = time.time()

    def expect_deletes(self, key: str, uids: list[str]) -> None:
        with self._lock:
            exp = self._by_key.setdefault(key, _Expectation())
            exp.deletes.update(uids)
            exp.stamp = time.time()

    def observe_create(self, key: str, uid: str) -> None:
        with self._lock:
            exp = self._by_key.get(key)
            if exp:
                exp.creates.discard(uid)

    def observe_delete(self, key: str, uid: str) -> None:
        with self._lock:
            exp = self._by_key.get(key)
            if exp:
                exp.deletes.discard(uid)

    def satisfied(self, key: str) -> bool:
        """True when all expected events have been observed (or expired —
        expired expectations are dropped so a lost event can't wedge the
        controller forever; the next sync recomputes from live state)."""
        with self._lock:
            exp = self._by_key.get(key)
            if exp is None:
                return True
            if not exp.creates and not exp.deletes:
                del self._by_key[key]
                return True
            if time.time() - exp.stamp > self._ttl:
                del self._by_key[key]
                return True
            return False

    def forget(self, key: str) -> None:
        with self._lock:
            self._by_key.pop(key, None)
