"""Sampling profiler — the pprof/Pyroscope analog.

The reference exposes a controller-runtime pprof endpoint gated by config
(operator api/config/v1alpha1/types.go:186, wired at
internal/controller/manager.go:115-123) and its scale harness captures
per-phase profiles pushed to Pyroscope (e2e/tests/scale/scale_test.go:131,
hack/infra_manager/pyroscope.py). This module is the standalone analog:

- ``StackSampler`` — a wall-clock sampler over ``sys._current_frames()``
  that sees EVERY thread (controllers, kubelets, HTTP handlers), not just
  the caller. Output is collapsed-stack format (``a;b;c N``), directly
  consumable by flamegraph tooling — the same shape Pyroscope ingests.
- ``dump_stacks`` — a point-in-time all-threads stack dump (the
  goroutine-dump analog, pprof's ``/debug/pprof/goroutine?debug=2``).
- ``PhaseProfiler`` — per-phase capture for the scale runner: each phase
  gets its own sampler; profiles export next to the timeline JSON (the
  Pyroscope-push analog without a Pyroscope).

Server wiring: ``GET /debug/profile`` and ``GET /debug/stacks`` in
grove_tpu/server.py, gated by ``OperatorConfiguration.profiling.enabled``
exactly as the reference gates pprof.

A sampling (not tracing) profiler is the right tool here: it has ~zero
overhead on the hot reconcile loops being measured, works across all
threads, and needs nothing outside the stdlib.
"""

from __future__ import annotations

import collections
import sys
import threading
import time
import traceback


def dump_stacks() -> str:
    """All-threads stack dump (goroutine-dump analog)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(ident, '?')} (id {ident}) ---")
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


def _collapse(frame) -> str:
    """One collapsed-stack line (root → leaf) for a frame."""
    parts: list[str] = []
    while frame is not None:
        code = frame.f_code
        mod = code.co_filename.rsplit("/", 1)[-1].removesuffix(".py")
        parts.append(f"{mod}.{code.co_name}")
        frame = frame.f_back
    return ";".join(reversed(parts))


class StackSampler:
    """Samples every thread's stack at a fixed interval from a background
    thread; aggregates identical stacks into counts."""

    def __init__(self, interval: float = 0.01):
        self.interval = interval
        self._counts: collections.Counter[str] = collections.Counter()
        self._samples = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._t0 = 0.0
        self.duration = 0.0

    def start(self) -> "StackSampler":
        assert self._thread is None, "sampler already started"
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(target=self._run,
                                        name="stack-sampler", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        me = threading.get_ident()
        while not self._stop.wait(self.interval):
            for ident, frame in sys._current_frames().items():
                if ident == me:
                    continue
                self._counts[_collapse(frame)] += 1
            self._samples += 1

    def stop(self) -> "StackSampler":
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self.duration = time.perf_counter() - self._t0
        return self

    @property
    def samples(self) -> int:
        return self._samples

    def collapsed(self) -> str:
        """Flamegraph-ready collapsed stacks, most frequent first."""
        return "\n".join(f"{stack} {n}" for stack, n in
                         self._counts.most_common()) + "\n"

    def top(self, n: int = 20) -> list[dict]:
        """Hottest leaf frames (self-time analog of ``pprof top``)."""
        leaves: collections.Counter[str] = collections.Counter()
        for stack, count in self._counts.items():
            leaves[stack.rsplit(";", 1)[-1]] += count
        total = sum(leaves.values()) or 1
        return [{"func": f, "samples": c, "pct": round(100.0 * c / total, 1)}
                for f, c in leaves.most_common(n)]


def profile_window(seconds: float, interval: float = 0.01) -> StackSampler:
    """Sample all threads for ``seconds``; returns the stopped sampler."""
    s = StackSampler(interval=interval).start()
    time.sleep(seconds)
    return s.stop()


class PhaseProfiler:
    """Per-phase capture for scale/soak runs (Pyroscope-push analog:
    one collapsed-stack artifact per phase, exported beside the timeline
    JSON so run-over-run diffs are possible)."""

    def __init__(self, enabled: bool = True, interval: float = 0.01):
        self.enabled = enabled
        self.interval = interval
        self.phases: dict[str, StackSampler] = {}
        self._active: tuple[str, StackSampler] | None = None

    def __enter__(self) -> "PhaseProfiler":
        return self

    def __exit__(self, *exc) -> None:
        if self._active is not None:
            self.end_phase()

    def begin_phase(self, name: str) -> None:
        if not self.enabled:
            return
        if self._active is not None:
            self.end_phase()
        self._active = (name, StackSampler(self.interval).start())

    def end_phase(self) -> None:
        if self._active is None:
            return
        name, sampler = self._active
        self.phases[name] = sampler.stop()
        self._active = None

    def export_dir(self, path: str) -> dict:
        """Write ``<phase>.collapsed`` per phase + a summary JSON; returns
        the summary dict."""
        import json
        import os

        os.makedirs(path, exist_ok=True)
        summary = {}
        for name, sampler in self.phases.items():
            with open(os.path.join(path, f"{name}.collapsed"), "w") as f:
                f.write(sampler.collapsed())
            summary[name] = {"duration_s": round(sampler.duration, 3),
                             "samples": sampler.samples,
                             "top": sampler.top(10)}
        with open(os.path.join(path, "profile-summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
        return summary
