"""Manager: owns the store, clients, controllers, and agents.

Role parity with reference internal/controller/manager.go:55-147 +
cmd/main.go:44-143. Leader election's single-writer guarantee lives at
the state-dir (flock + standby takeover, store/persist.py
_acquire_state_lock — a second `serve --state-dir X` is refused or
waits as a standby) plus the epoch fence (grove_tpu/ha): ``promote()``
bumps the store's fencing epoch and stamps this manager's control-plane
writers with it; ``demote()`` parks controllers (queued work dropped,
expectations cleared — the SURVEY §7 duplicate-pod hygiene) and pauses
writer runnables while leaving the stale epoch on the clients, so a
straggler write after a rival's takeover is REJECTED by the store
instead of racing the new leader. Webhook TLS is subsumed by admission
running in-process at the client boundary (see grove_tpu.admission).
"""

from __future__ import annotations

import threading
import time
from typing import Any

from grove_tpu.api.config import OperatorConfiguration, validate_config
from grove_tpu.runtime.controller import Controller
from grove_tpu.runtime.informer import CachedClient, InformerSet
from grove_tpu.runtime.logger import get_logger, setup_logging
from grove_tpu.runtime.trace import GLOBAL_TRACER
from grove_tpu.store.client import Client
from grove_tpu.store.store import Store


class Manager:
    def __init__(self, config: OperatorConfiguration | None = None,
                 store: Store | None = None, client: Client | None = None):
        self.config = config or OperatorConfiguration()
        problems = validate_config(self.config)
        if problems:
            raise ValueError(f"invalid operator configuration: {problems}")
        setup_logging(self.config.log.level, self.config.log.format)
        self.store = store or Store()
        self.client = client or Client(self.store)
        # The control plane's OWN writer identity, separate from
        # self.client: schedulers, node-lifecycle, autoscaler, and
        # defrag write through this one so promotion can stamp it with
        # the fencing epoch WITHOUT fencing the data plane (kubelets
        # and agents keep self.client — in a real failover the node
        # fleet re-targets the new leader; it is never "deposed").
        self.leader_client = Client(self.store, self.client.actor)
        # Leadership view (grove_tpu/ha): single-replica default is
        # "leader with epoch 0, clients unfenced" — exactly the pre-HA
        # behavior until someone campaigns (elector, standby promote,
        # chaos transition).
        from grove_tpu.ha.election import LeadershipState
        self.leadership = LeadershipState(
            replica=getattr(self.config, "ha", None)
            and self.config.ha.replica or "")
        self.leadership.epoch = self.store.fencing_epoch()
        # Stamp the control-plane writers with the CURRENT term from
        # the start: at epoch N a claim of N is always accepted (no
        # behavior change for a single replica), but the moment a
        # rival campaigns (bump to N+1) every write this manager's
        # controllers/schedulers still have in flight is fenced — the
        # zombie guard must not depend on this replica having formally
        # campaigned first.
        self.leader_client.epoch = self.leadership.epoch
        # Shared informer layer (one watch cache per kind, shared by
        # every controller in this manager — the SharedInformerFactory
        # role); controllers read through cached_client, everything
        # else (agents, schedulers, user surfaces) keeps the direct
        # client. GROVE_INFORMER=0 routes cached reads back to the
        # store per call.
        self.informers = InformerSet(store=self.store)
        self.cached_client = CachedClient(self.client, self.informers)
        self.cached_client.epoch = self.leader_client.epoch
        # Lifecycle tracer handle (the flight recorder every pipeline
        # stage appends spans to); the server serves it at
        # /debug/traces through this handle, not the global.
        self.tracer = GLOBAL_TRACER
        self.log = get_logger("manager")
        self.controllers: list[Controller] = []
        self.runnables: list[Any] = []   # agents etc. with start()/stop()
        # Deploy observatory: per-PCS rollout progress fed by the store
        # event stream (served at /debug/deploy and by grovectl
        # deploy-status). A runnable so it starts/stops with the
        # manager's control loops.
        from grove_tpu.runtime.deploywatch import DeployObserver
        self.deploy_observer = DeployObserver(self.store)
        self.runnables.append(self.deploy_observer)
        # Control-plane observatory (runtime/sweepobs.py): per-sweep
        # reconcile attribution + write-amplification ledger, served at
        # /debug/controlplane. A runnable for registry lifecycle only —
        # it has no thread; controllers feed it synchronously.
        from grove_tpu.runtime.sweepobs import SweepObserver
        self.sweep_observer = SweepObserver(self.store)
        self.sweep_observer.attach_informers(self.informers)
        self.runnables.append(self.sweep_observer)
        self._started = False

    def add_controller(self, controller: Controller) -> None:
        controller.sweep_observer = self.sweep_observer
        self.controllers.append(controller)

    def add_runnable(self, runnable: Any) -> None:
        self.runnables.append(runnable)

    def start(self) -> None:
        if self._started:
            return      # idempotent: a promoted cluster may be handed
            #             to a `with` block that starts it again
        from grove_tpu.ha.election import register_leadership
        register_leadership(self.store, self.leadership)
        for c in self.controllers:
            c.start()
        for r in self.runnables:
            r.start()
        self._started = True
        self.log.info("manager started: %d controllers, %d runnables",
                      len(self.controllers), len(self.runnables))

    def stop(self) -> None:
        # Two-phase shutdown: signal everything first, then join.
        # Joining controller-by-controller would serialize each one's
        # dispatch-poll drain (~0.2s) because the NEXT controller's
        # stop flag isn't set until the previous join returns.
        for c in self.controllers:
            c.request_stop()
        for r in self.runnables:
            request = getattr(r, "request_stop", None)
            if callable(request):
                request()
        for c in self.controllers:
            c.stop()
        for r in self.runnables:
            r.stop()
        self._started = False

    # ---- leadership transitions (grove_tpu/ha, proposal 0002) ----

    def promote(self) -> int:
        """Become (or re-become) the reconciling leader: bump the
        store's fencing epoch (durable before the first write under the
        new term), stamp this manager's control-plane writers with it,
        un-park controllers (each re-syncs its watches so the queue
        rebuilds from live state — the warm-start reconcile), and
        resume paused writer runnables. Returns the new epoch."""
        epoch = self.store.bump_epoch()
        self.leader_client.epoch = epoch
        self.cached_client.epoch = epoch
        for c in self.controllers:
            c.unpark()
        for r in self.runnables:
            resume = getattr(r, "resume", None)
            if callable(resume):
                resume()
        self.leadership.note_promoted(epoch)
        self._record_transition_event("LeaderElected",
                                      f"replica promoted at epoch {epoch}")
        self.log.info("promoted: epoch=%d (%d controllers resynced)",
                      epoch, len(self.controllers))
        return epoch

    def demote(self, leader_hint: str = "") -> int:
        """Stand down after losing leadership: park every controller
        (queued work DROPPED — it was computed under a now-stale view),
        clear their expectation stores (stale expectations on a later
        re-promotion are exactly the SURVEY §7 duplicate-pod hazard),
        and pause writer runnables. The clients KEEP their stale epoch:
        that is the fence — an in-flight reconcile finishing after this
        returns gets FencedError from the store, not a committed write.
        Returns the number of dropped queue items."""
        self.leadership.note_demoted(leader_hint)
        dropped = 0
        for c in self.controllers:
            dropped += c.park()
        for r in self.runnables:
            pause = getattr(r, "pause", None)
            if callable(pause):
                pause()
        self._record_transition_event(
            "LeaderDemoted",
            f"replica demoted (dropped {dropped} queued items"
            + (f"; leader: {leader_hint}" if leader_hint else "") + ")")
        self.log.info("demoted: %d queued items dropped, runnables "
                      "paused", dropped)
        return dropped

    def _record_transition_event(self, reason: str, message: str) -> None:
        """Promotion/demotion event pair, written through an UNFENCED
        client on purpose: a demoted replica must still be able to
        leave its demotion in the event log (its fenced clients could
        not). Best-effort like all events."""
        try:
            from grove_tpu.runtime.events import Event
            from grove_tpu.api.meta import new_meta
            import time as _time
            now = _time.time()
            name = (f"leadership.{self.leadership.replica}."
                    f"{reason.lower()}.{self.leadership.transitions}")
            Client(self.store).create(Event(
                meta=new_meta(name, labels={"component": "ha"}),
                involved_kind="Manager",
                involved_name=self.leadership.replica,
                type="Normal", reason=reason, message=message,
                first_seen=now, last_seen=now))
        except Exception:  # noqa: BLE001 — observability must not block
            pass           # a transition (duplicate names included)

    # ---- health/readiness (reference manager.go:73-89) ----

    def metrics_text(self) -> str:
        """Prometheus text exposition (the metrics-server analog)."""
        from grove_tpu.manifest import KIND_REGISTRY
        from grove_tpu.runtime.metrics import GLOBAL_METRICS
        # Gauge-family semantics for the point-sampled queue depths: a
        # controller that stopped (or drained out of this manager)
        # must zero its series on the next scrape, not linger at the
        # last sampled depth forever.
        GLOBAL_METRICS.set_gauge_family(
            "grove_workqueue_depth",
            [({"controller": c.name}, float(len(c.queue)))
             for c in self.controllers])
        for kind, cls in KIND_REGISTRY.items():
            try:
                GLOBAL_METRICS.set("grove_store_objects",
                                   len(self.client.list(cls, namespace=None)),
                                   kind=kind)
            except Exception:  # noqa: BLE001 - best-effort gauge
                pass
        self._export_state_objects()
        # Sweep observatory gauges (write-amp per controller, watch-lag
        # SLO per kind) — re-asserted per scrape like the rest; parked
        # controllers zero via the family setter.
        self.sweep_observer.export_gauges()
        # Leadership gauges re-asserted per scrape (a scrape between
        # transitions must still see the current role/epoch).
        GLOBAL_METRICS.set("grove_leader",
                           1.0 if self.leadership.is_leader else 0.0,
                           replica=self.leadership.replica)
        GLOBAL_METRICS.set("grove_leadership_epoch",
                           float(self.store.fencing_epoch()))
        return GLOBAL_METRICS.render()

    def _export_state_objects(self) -> None:
        """kube-state-metrics-style ``grove_state_objects{kind,phase}``
        gauges, fed from the shared informer caches (one indexed cache
        read per kind, not a store scan per scrape; kinds the informer
        layer refuses to cache — Secrets — are skipped). The
        gauge-family setter zeroes phases that drained since the last
        scrape so alerts clear."""
        from grove_tpu.manifest import KIND_REGISTRY
        from grove_tpu.runtime.metrics import GLOBAL_METRICS
        series: list[tuple[dict, float]] = []
        for kind, cls in KIND_REGISTRY.items():
            lister = self.informers.lister(cls)
            if lister is None:
                continue
            try:
                counts: dict[str, int] = {}
                for obj in lister.list(namespace=None):
                    phase = getattr(getattr(obj, "status", None),
                                    "phase", "")
                    phase = getattr(phase, "value", phase) or ""
                    counts[phase] = counts.get(phase, 0) + 1
            except Exception:  # noqa: BLE001 - best-effort gauge
                continue
            series.extend(({"kind": kind, "phase": phase}, float(n))
                          for phase, n in counts.items())
        GLOBAL_METRICS.set_gauge_family("grove_state_objects", series)

    def healthz(self) -> dict:
        return {
            "started": self._started,
            "controllers": {
                c.name: {"queue": len(c.queue),
                         "reconciles": c.reconcile_count,
                         "errors": c.error_count}
                for c in self.controllers
            },
        }

    def wait_idle(self, timeout: float = 10.0, settle: float = 0.2) -> bool:
        """Block until all controller queues stay empty for ``settle``
        seconds (test convenience; the e2e 'waiter' analog)."""
        deadline = time.time() + timeout
        quiet_since = None
        while time.time() < deadline:
            if all(len(c.queue) == 0 for c in self.controllers):
                if quiet_since is None:
                    quiet_since = time.time()
                elif time.time() - quiet_since >= settle:
                    return True
            else:
                quiet_since = None
            time.sleep(0.02)
        return False
