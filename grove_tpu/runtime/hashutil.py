"""Template hashing for change detection.

Role parity with reference internal/utils/kubernetes ComputeHash + the
generation-hash machinery (podcliqueset/reconcilespec.go:110-123): a
stable short hash of the pod-shaping parts of a spec, used to detect
rolling-update triggers and to label pods with their template version.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any

from grove_tpu.api.serde import to_dict


def compute_hash(obj: Any) -> str:
    data = json.dumps(to_dict(obj), sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(data.encode()).hexdigest()[:10]
