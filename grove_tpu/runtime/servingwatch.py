"""Serving observatory — control-plane aggregation of engine SLO state.

The third leg of the observability tripod: PR 3 traced gang LIFECYCLE,
PR 6 watched the deploy WRITE path, this watches the SERVING loop. The
engines inside pods stamp every request (serving/slo.py) and push
percentile digests to ``/metrics/push`` (serving/metrics_push.py,
batched); the ``ServingObserver`` runnable sweeps the MetricsRegistry
on a timer and turns the per-reporter soup into per-scope answers:

- ``grove_serving_signal{kind,name,metric}`` — every fresh aggregated
  series (queue depth summed, KV utilization averaged, p99 TTFT maxed
  — the registry's per-metric aggregation modes applied),
- ``grove_serving_reporters{kind,name}`` — live reporter count (a
  2-replica PCSG reporting from one engine is a liveness finding, not
  a latency one),
- ``grove_serving_slo_breached{kind,name}`` — 1 while the scope's
  autoscaling target metric exceeds its target (the alertable twin of
  the Autoscaler's scale-out trigger),

all exported through ``set_gauge_family`` so a drained scope zeroes
instead of lingering at its last value.

Surfaces (the deploy-observatory pattern):
- ``GET /debug/serving/<ns>/<name>`` (server.py; read-gated),
- ``Client.debug_serving`` / ``HttpClient.debug_serving`` twins,
- ``grovectl serving-status <name>`` renders it
  (render_serving_status).
"""

from __future__ import annotations

import threading
import time
import weakref

from grove_tpu.api import PodClique, PodCliqueScalingGroup, PodCliqueSet
from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.metrics import GLOBAL_METRICS

# store (weakly) -> its serving observer, so the in-process Client can
# resolve debug_serving without a manager reference (the deploywatch
# _OBSERVERS precedent).
_OBSERVERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def serving_observer_for(store) -> "ServingObserver | None":
    return _OBSERVERS.get(store)


class ServingObserver:
    """Registry-sweeping SLO aggregator (a manager runnable)."""

    def __init__(self, client, metrics, store, tick: float = 0.5) -> None:
        self.client = client
        self.metrics = metrics
        # Weak store ref (deploywatch precedent: _OBSERVERS strongly
        # references its values, so a strong ref here would leak every
        # discarded Manager's store for process lifetime).
        self._store_ref = weakref.ref(store)
        self.tick = tick
        self.log = get_logger("servingwatch")
        from grove_tpu.analysis import lockdep
        self._lock = lockdep.maybe_wrap(threading.Lock(), "serving-observer")
        # (namespace, name) -> list of per-kind scope dicts (payload()).
        self._state: dict[tuple[str, str], list[dict]] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ---- lifecycle (manager runnable contract) ----

    def start(self) -> None:
        store = self._store_ref()
        if store is None:
            return
        # Registered on START so a constructed-but-unstarted Manager
        # can't shadow the running observer; survives stop() so the
        # last state stays inspectable.
        _OBSERVERS[store] = self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="serving-observer",
                                        daemon=True)
        self._thread.start()

    def request_stop(self) -> None:
        """Signal-only phase of the manager's two-phase shutdown."""
        self._stop.set()

    def stop(self) -> None:
        self.request_stop()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sweep()
            except Exception:  # noqa: BLE001 - observer must not die
                self.log.exception("serving sweep panicked")
            self._stop.wait(self.tick)

    # ---- the sweep ----

    def _autoscaled(self) -> dict[tuple[str, str, str], dict]:
        """(kind, namespace, name) -> {target metric, target value,
        replicas, ready} for every scalable object — the SLO targets
        the signals are judged against."""
        out: dict[tuple[str, str, str], dict] = {}
        for kind_cls in (PodClique, PodCliqueScalingGroup, PodCliqueSet):
            try:
                objs = self.client.list(kind_cls, None)
            except Exception:  # noqa: BLE001 - sweep survives a bad list
                continue
            for obj in objs:
                a = obj.spec.auto_scaling
                st = obj.status
                ready = getattr(st, "ready_replicas",
                                getattr(st, "available_replicas", 0))
                out[(obj.KIND, obj.meta.namespace, obj.meta.name)] = {
                    "metric": a.metric if a else None,
                    "target": a.target_value if a else None,
                    "replicas": obj.spec.replicas,
                    "ready_replicas": ready,
                }
        return out

    def sweep(self) -> None:
        """One aggregation pass: registry → gauges + payload state.
        Public so smokes/benches can force a scrape without waiting a
        tick."""
        fresh = self.metrics.all_fresh()
        targets = self._autoscaled()
        # (kind, ns, name) -> {metric: {value, agg, reporters}}
        scopes: dict[tuple[str, str, str], dict[str, dict]] = {}
        for kind, ns, name, metric, value, agg, reporters in fresh:
            scopes.setdefault((kind, ns, name), {})[metric] = {
                "value": value, "agg": agg, "reporters": reporters}
        signal_series: list[tuple[dict, float]] = []
        reporter_series: list[tuple[dict, float]] = []
        breach_series: list[tuple[dict, float]] = []
        state: dict[tuple[str, str], list[dict]] = {}
        for (kind, ns, name), metrics_map in sorted(scopes.items()):
            # Labels carry the namespace: same-named scopes in two
            # namespaces are distinct series, not a last-writer-wins
            # collision (a healthy ns/b must never mask a breached
            # ns/a on the alertable gauge).
            scope_labels = {"kind": kind, "namespace": ns, "name": name}
            for metric, entry in metrics_map.items():
                signal_series.append(
                    (dict(scope_labels, metric=metric), entry["value"]))
            reporter_series.append(
                (scope_labels,
                 float(max(e["reporters"] for e in metrics_map.values()))))
            tgt = targets.get((kind, ns, name))
            slo = None
            if tgt and tgt["metric"] and tgt["metric"] in metrics_map \
                    and tgt["target"]:
                current = metrics_map[tgt["metric"]]["value"]
                breached = current > tgt["target"]
                slo = {"metric": tgt["metric"], "target": tgt["target"],
                       "current": current, "breached": breached}
                breach_series.append((scope_labels,
                                      1.0 if breached else 0.0))
            state.setdefault((ns, name), []).append({
                "kind": kind,
                "metrics": metrics_map,
                "slo": slo,
                "replicas": tgt["replicas"] if tgt else None,
                "ready_replicas": tgt["ready_replicas"] if tgt else None,
            })
        GLOBAL_METRICS.set_gauge_family("grove_serving_signal",
                                        signal_series)
        GLOBAL_METRICS.set_gauge_family("grove_serving_reporters",
                                        reporter_series)
        GLOBAL_METRICS.set_gauge_family("grove_serving_slo_breached",
                                        breach_series)
        with self._lock:
            self._state = state

    # ---- read surface ----

    def payload(self, namespace: str, name: str) -> dict | None:
        """The /debug/serving payload for one scope name, or None when
        no engine has reported fresh samples for it. ``kv_headroom`` is
        derived (1 - utilization) so the renderer and alerts share one
        definition."""
        with self._lock:
            scopes = self._state.get((namespace, name))
            if scopes is None:
                return None
            scopes = [dict(s, metrics=dict(s["metrics"])) for s in scopes]
        for s in scopes:
            util = s["metrics"].get("kv_utilization")
            s["kv_headroom"] = (round(1.0 - util["value"], 4)
                                if util else None)
        return {
            "namespace": namespace,
            "name": name,
            "now": time.time(),
            "sample_ttl": self.metrics.sample_ttl,
            "scopes": scopes,
        }


def render_serving_status(payload: dict) -> list[str]:
    """Human rendering of a /debug/serving payload — the ``grovectl
    serving-status`` body (kept beside the observer so the CLI and
    tests share one renderer; the render_deploy_status precedent)."""
    out = []
    name = payload.get("name", "?")
    for scope in payload.get("scopes", []):
        kind = scope.get("kind", "?")
        head = f"{kind}/{name}"
        reps = scope.get("replicas")
        if reps is not None:
            head += (f": {scope.get('ready_replicas', 0)}/{reps} "
                     "replicas ready")
        slo = scope.get("slo")
        if slo:
            verdict = "BREACHED" if slo["breached"] else "ok"
            head += (f"  SLO {slo['metric']} {slo['current']:.1f} "
                     f"vs target {slo['target']:g} [{verdict}]")
        out.append(head)
        metrics = scope.get("metrics", {})
        for metric in sorted(metrics):
            e = metrics[metric]
            out.append(f"  {metric:<22} {e['value']:>10.2f}  "
                       f"({e['agg']} over {e['reporters']} reporter"
                       f"{'s' if e['reporters'] != 1 else ''})")
        if scope.get("kv_headroom") is not None:
            out.append(f"  {'kv_headroom':<22} "
                       f"{scope['kv_headroom']:>10.2f}  (derived)")
    if not out:
        out.append(f"{name}: no fresh serving samples")
    return out
