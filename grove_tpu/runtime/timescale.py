"""The single source of timing truth for wall-clock-sensitive waits.

The container's CPU shares are throttled unpredictably: identical code
has swung the full suite 155s -> 259s (CHANGES.md PR 6), and on the
slow-wall runs the tightest polling deadlines flaked — each passes in
isolation; only the deadline was wrong, not the code.

Every polling deadline therefore scales through ``TIME_SCALE`` at one
chokepoint per consumer (``test_e2e_simple.wait_for`` for the test
suite, ``chaos.invariants``/``chaos.scenario`` for the chaos harness),
instead of each call site hand-picking a number that is right on a
fast box and wrong on a throttled one. A scaled deadline costs nothing
when the condition arrives early — the waiters poll, they never sleep
the deadline out — so the default is generous.

This lives in the package (not under tests/) because the chaos harness
ships as ``grove_tpu.chaos`` and must scale its invariant deadlines
with the same knob the tests use; ``tests/timing.py`` re-exports it so
the test suite's import surface is unchanged.

``GROVE_TEST_TIME_SCALE`` overrides it: crank it up on a known-slow
runner, set it to 1 to reproduce a deadline-tightness flake locally.
"""

from __future__ import annotations

import os

TIME_SCALE = max(0.1, float(os.environ.get("GROVE_TEST_TIME_SCALE", "3.0")))


def scaled(seconds: float) -> float:
    """A wall-clock deadline adjusted for this machine's slowness."""
    return seconds * TIME_SCALE
