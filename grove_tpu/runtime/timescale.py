"""The single source of timing truth for wall-clock-sensitive waits.

The container's CPU shares are throttled unpredictably: identical code
has swung the full suite 155s -> 259s (CHANGES.md PR 6), and on the
slow-wall runs the tightest polling deadlines flaked — each passes in
isolation; only the deadline was wrong, not the code.

Every polling deadline therefore scales through ``TIME_SCALE`` at one
chokepoint per consumer (``test_e2e_simple.wait_for`` for the test
suite, ``chaos.invariants``/``chaos.scenario`` for the chaos harness),
instead of each call site hand-picking a number that is right on a
fast box and wrong on a throttled one. A scaled deadline costs nothing
when the condition arrives early — the waiters poll, they never sleep
the deadline out — so the default is generous.

This lives in the package (not under tests/) because the chaos harness
ships as ``grove_tpu.chaos`` and must scale its invariant deadlines
with the same knob the tests use; ``tests/timing.py`` re-exports it so
the test suite's import surface is unchanged.

``GROVE_TEST_TIME_SCALE`` overrides it: crank it up on a known-slow
runner, set it to 1 to reproduce a deadline-tightness flake locally.
"""

from __future__ import annotations

import os
import time

DEFAULT_SCALE = 3.0

TIME_SCALE = max(0.1, float(os.environ.get("GROVE_TEST_TIME_SCALE",
                                           str(DEFAULT_SCALE))))


def scaled(seconds: float) -> float:
    """A wall-clock deadline adjusted for this machine's slowness."""
    return seconds * TIME_SCALE


# The factor settle() applies: 1.0 at (or below) the default scale,
# proportional above it. Exported so a test whose subject has a REAL
# wall-clock window (e.g. the autoscaler's scale-down stabilization)
# can scale that window by the same factor as its settles — keeping
# the before/after-the-window ratios invariant at any scale.
SETTLE_SCALE = max(1.0, TIME_SCALE / DEFAULT_SCALE)


def settle(seconds: float) -> None:
    """Sleep a settle floor — the "give the system time to do the
    wrong thing" wait before a negative assertion, or a propagation
    floor a poll can't replace.

    Unlike a polled deadline, a sleep ALWAYS pays its full duration,
    so settles scale relative to the DEFAULT scale rather than by raw
    TIME_SCALE: at the default configuration this is exactly
    ``time.sleep(seconds)`` (no suite-wide slowdown for the common
    case), while a known-slow runner that cranks GROVE_TEST_TIME_SCALE
    above the default gets proportionally longer settles. Floored at
    1x — a settle is a minimum, shrinking it changes what the test
    means. Grovelint's raw-test-sleep rule points here."""
    time.sleep(seconds * SETTLE_SCALE)
