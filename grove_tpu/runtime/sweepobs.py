"""Control-plane observatory — per-sweep reconcile attribution.

PR 19 gave the data plane per-request attribution; this module is the
control plane's twin (ROADMAP item 5: the write-amp gap must close in a
way "the observatory can prove"). Every reconcile sweep a controller
runs is recorded end-to-end:

- **trigger cause** from the workqueue hint (``runtime/controller.py``
  rides it next to the trace hint): ``watch:<Kind>`` for a watch event,
  ``resync`` for the startup/unpark relist, ``requeue`` for an explicit
  requeue_after, ``backoff``/``panic`` for the failure ladder,
  ``external`` for direct enqueues (scale runners, tests);
- **store attribution** via the existing writeobs contextvar records: a
  sweep sink rides a *contextvar* (NOT a thread-local — fan-out through
  ``runtime/concurrent.py`` copies the context onto pool threads, so a
  pod-creation burst's writes land in the sweep that issued them, the
  same reason writer attribution survives there). Each flushed
  ``WriteRecord`` folds into the open sweep: write-verb calls, commits
  (= changed objects), no-ops, conflicts, fenced rejections, list
  scans, and the store-lock wait/hold split;
- **wall split**: lock-wait (Σ record wait), store-write (Σ record
  hold), compute (the remainder). Queue pickup-to-done is already
  ``grove_workqueue_work_seconds``; this carves up the "being worked
  on" half.

Rolled-up series (pinned buckets, runtime/metrics.py):

- ``grove_sweep_seconds{controller,cause}`` — sweep wall time;
- ``grove_sweep_writes{controller,verb}`` — write-verb calls per sweep
  (a batched ``patch_status_many`` is ONE call however many items — the
  store-RPC-rate analog batching is supposed to bend);
- ``grove_sweep_write_amp{controller}`` — recent writes per changed
  object (gauge, re-asserted per scrape; zeroed on park/demote);
- ``grove_informer_watch_lag_seconds{kind}`` /
  ``grove_informer_watch_lag_breached{kind}`` — the watch-lag SLO
  gauges, judged against ``GROVE_WATCH_LAG_SLO`` (seconds).

The **write-amplification ledger** keeps per-controller totals plus a
sweep-over-sweep recent window (writes per changed object) and a
hot-object top-K so one flapping PodCliqueSet can be *named*, not just
suspected from an aggregate.

Surfaces (the house observatory pattern, deploywatch.py's sibling):
``GET /debug/controlplane`` (read-gated), ``Client``/``HttpClient``
``debug_controlplane`` twins, ``grovectl controlplane-status`` (hottest
controller starred; exit 1 on a watch-lag breach or write-amp above
threshold), a bench_dashboard section, and ``tools/controlplane_smoke``
in ``make ci``.

Off switch: ``GROVE_SWEEP_OBS=0`` (per-call env read, the
GROVE_WRITE_OBS idiom) restores the exact prior reconcile path —
tests/test_sweepobs.py pins the dual-estimator overhead under 5%. The
sweep sink only sees what writeobs records, so ``GROVE_WRITE_OBS=0``
also blinds the ledger's write columns (documented, not a bug).
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
import weakref
from typing import Any, Iterator

from grove_tpu.runtime.metrics import GLOBAL_METRICS
from grove_tpu.store import writeobs

SWEEP_OBS_ENV = "GROVE_SWEEP_OBS"
WATCH_LAG_SLO_ENV = "GROVE_WATCH_LAG_SLO"

# Default staleness target for the watch-lag SLO (seconds). In-process
# informers apply at micro-to-millisecond lag; a full second of
# staleness means the watch path is drowning (or replaying a gap).
DEFAULT_WATCH_LAG_SLO_S = 1.0

# grovectl's default write-amp alarm threshold (writes per changed
# object over the recent window). A healthy reconcile writes once per
# object it changes (amp ~1); no-op storms and conflict retries push it
# up. 10 is loud enough to mean "a controller is flapping".
DEFAULT_WRITE_AMP_THRESHOLD = 10.0

# Recent window for the sweep-over-sweep amplification estimate.
RECENT_SWEEPS = 64

# Hot-object table bound: trimmed to the top half when it doubles.
HOT_CAPACITY = 4096

# store (weakly) -> its observer, so the in-process Client can resolve
# the payload the same way HTTP does (the deploywatch registry idiom).
_OBSERVERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def observer_for(store) -> "SweepObserver | None":
    return _OBSERVERS.get(store)


def enabled() -> bool:
    """Per-call env read (the GROVE_WRITE_OBS idiom): flipping
    ``GROVE_SWEEP_OBS=0`` mid-process takes effect on the next sweep —
    incident mitigation and the overhead benchmark's baseline."""
    return os.environ.get(SWEEP_OBS_ENV, "1") != "0"


def watch_lag_slo_s() -> float:
    try:
        return float(os.environ.get(WATCH_LAG_SLO_ENV,
                                    str(DEFAULT_WATCH_LAG_SLO_S)))
    except ValueError:
        return DEFAULT_WATCH_LAG_SLO_S


class SweepSink:
    """Per-sweep write accumulator, fed by writeobs.flush/count_scan.

    Thread-safe on purpose: the sink rides a contextvar through
    ``run_concurrently``'s context copy, so a slow-start pod-creation
    burst has many pool threads absorbing into ONE sink concurrently.
    """

    __slots__ = ("_lock", "verb_calls", "commits", "noops", "conflicts",
                 "fenced", "scans", "wait_s", "hold_s")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.verb_calls: collections.Counter = collections.Counter()
        self.commits = 0
        self.noops = 0
        self.conflicts = 0
        self.fenced = 0
        self.scans = 0
        self.wait_s = 0.0
        self.hold_s = 0.0

    def absorb(self, rec: "writeobs.WriteRecord") -> None:
        """Fold one flushed WriteRecord into the sweep (called by
        writeobs.flush AFTER the store lock is released)."""
        with self._lock:
            self.verb_calls[rec.verb] += 1
            self.commits += len(rec.commits)
            self.noops += len(rec.noops)
            self.conflicts += len(rec.conflicts)
            self.fenced += len(rec.fenced)
            self.scans += len(rec.scans)
            self.wait_s += rec.wait_s
            self.hold_s += rec.hold_s

    def absorb_scan(self, kind: str) -> None:
        """A list-shaped read outside any write verb (the common list
        path) — counted as scanned work, no verb call."""
        with self._lock:
            self.scans += 1

    def write_calls(self) -> int:
        with self._lock:
            return sum(self.verb_calls.values())


class _Ledger:
    """Per-controller write-amplification ledger entry."""

    __slots__ = ("sweeps", "causes", "wall_s", "lock_wait_s",
                 "store_write_s", "compute_s", "write_calls", "commits",
                 "noops", "conflicts", "fenced", "scans", "verb_calls",
                 "recent", "last")

    def __init__(self) -> None:
        self.sweeps = 0
        self.causes: collections.Counter = collections.Counter()
        self.wall_s = 0.0
        self.lock_wait_s = 0.0
        self.store_write_s = 0.0
        self.compute_s = 0.0
        self.write_calls = 0
        self.commits = 0
        self.noops = 0
        self.conflicts = 0
        self.fenced = 0
        self.scans = 0
        self.verb_calls: collections.Counter = collections.Counter()
        # Sweep-over-sweep recent window: (write_calls, commits) per
        # sweep, the basis of the windowed amplification estimate.
        self.recent: "collections.deque[tuple[int, int]]" = \
            collections.deque(maxlen=RECENT_SWEEPS)
        self.last: dict[str, Any] = {}

    def recent_amp(self) -> float:
        writes = sum(w for w, _ in self.recent)
        changed = sum(c for _, c in self.recent)
        return writes / max(1, changed)

    def total_amp(self) -> float:
        return self.write_calls / max(1, self.commits)


class SweepObserver:
    """The control-plane observatory: holds the per-controller ledger
    and emits the rolled-up sweep series. A manager runnable (started
    and stopped with the control loops) with no thread of its own — it
    is fed synchronously from ``Controller._process`` via ``record()``,
    not from the event stream."""

    def __init__(self, store) -> None:
        # Weak store ref: _OBSERVERS is weakly KEYED by the store, and a
        # strong ref from value back to key would pin the entry forever.
        self._store_ref = weakref.ref(store)
        from grove_tpu.analysis import lockdep
        self._lock = lockdep.maybe_wrap(threading.Lock(), "sweep-observer")
        self._ledgers: dict[str, _Ledger] = {}
        # (controller, key) -> [write_calls, commits, sweeps]; bounded.
        self._hot: dict[tuple[str, str], list[int]] = {}
        self._parked: set[str] = set()
        self._paused = False
        self._informers_ref: Any = None

    # ---- runnable contract (Manager.runnables) ----

    def start(self) -> None:
        store = self._store_ref()
        if store is not None:
            _OBSERVERS[store] = self

    def request_stop(self) -> None:
        pass

    def stop(self) -> None:
        pass

    def pause(self) -> None:
        """Demotion (Manager.demote): a standby must not advertise live
        control-plane load — zero every sweep gauge family now rather
        than waiting for the next scrape to rebuild them."""
        self._paused = True
        GLOBAL_METRICS.set_gauge_family("grove_sweep_write_amp", [])
        GLOBAL_METRICS.set_gauge_family("grove_informer_watch_lag_seconds",
                                        [])
        GLOBAL_METRICS.set_gauge_family("grove_informer_watch_lag_breached",
                                        [])

    def resume(self) -> None:
        self._paused = False

    def attach_informers(self, informer_set) -> None:
        """Wire the manager's shared informers for the watch-lag SLO
        judge (weakly — the observer must not pin the manager's store
        through InformerSet)."""
        self._informers_ref = weakref.ref(informer_set)

    # ---- park hygiene (satellite: stale gauges on a standby) ----

    def on_park(self, controller: str) -> None:
        with self._lock:
            self._parked.add(controller)
        GLOBAL_METRICS.set("grove_sweep_write_amp", 0.0,
                           controller=controller)

    def on_unpark(self, controller: str) -> None:
        with self._lock:
            self._parked.discard(controller)

    # ---- recording ----

    @contextlib.contextmanager
    def record(self, controller: str, cause: str,
               key: str) -> Iterator[SweepSink | None]:
        """Attribute one reconcile sweep: installs the writeobs sweep
        sink for the duration of the body, then folds the sweep into
        the ledger and the rolled-up histograms. With GROVE_SWEEP_OBS=0
        this is a bare yield — the exact prior path."""
        if not enabled():
            yield None
            return
        sink = SweepSink()
        token = writeobs.set_sweep_sink(sink)
        t0 = time.perf_counter()
        try:
            yield sink
        finally:
            writeobs.reset_sweep_sink(token)
            self._ingest(controller, cause or "external", key,
                         time.perf_counter() - t0, sink)

    def _ingest(self, controller: str, cause: str, key: str,
                wall_s: float, sink: SweepSink) -> None:
        write_calls = sink.write_calls()
        compute_s = max(0.0, wall_s - sink.wait_s - sink.hold_s)
        with self._lock:
            led = self._ledgers.get(controller)
            if led is None:
                led = self._ledgers[controller] = _Ledger()
            led.sweeps += 1
            led.causes[cause] += 1
            led.wall_s += wall_s
            led.lock_wait_s += sink.wait_s
            led.store_write_s += sink.hold_s
            led.compute_s += compute_s
            led.write_calls += write_calls
            led.commits += sink.commits
            led.noops += sink.noops
            led.conflicts += sink.conflicts
            led.fenced += sink.fenced
            led.scans += sink.scans
            led.verb_calls.update(sink.verb_calls)
            led.recent.append((write_calls, sink.commits))
            led.last = {"cause": cause, "key": key,
                        "wall_s": wall_s, "write_calls": write_calls,
                        "changed": sink.commits, "noops": sink.noops,
                        "conflicts": sink.conflicts}
            if write_calls or sink.commits:
                hot = self._hot.get((controller, key))
                if hot is None:
                    hot = self._hot[(controller, key)] = [0, 0, 0]
                hot[0] += write_calls
                hot[1] += sink.commits
                hot[2] += 1
                if len(self._hot) > 2 * HOT_CAPACITY:
                    keep = sorted(self._hot.items(),
                                  key=lambda kv: kv[1][0],
                                  reverse=True)[:HOT_CAPACITY]
                    self._hot = dict(keep)
        # Hub emissions AFTER the observer lock (and writeobs already
        # released the store lock): one bulk, pre-sorted label tuples —
        # the hub's lock is held across every /metrics render.
        observations = [("grove_sweep_seconds",
                         _sweep_labels(cause, controller), wall_s)]
        for verb, n in sink.verb_calls.items():
            observations.append(("grove_sweep_writes",
                                 _write_labels(controller, verb),
                                 float(n)))
        GLOBAL_METRICS.bulk(observations=observations)

    # ---- export + payload ----

    def export_gauges(self) -> None:
        """Re-assert the sweep gauge families for one scrape
        (Manager.metrics_text). Parked controllers are omitted — the
        family setter zeroes their series (the satellite: a demoted
        standby's gauges must read 0, not last-known load)."""
        if self._paused:
            return
        with self._lock:
            amp_series = [({"controller": name}, led.recent_amp())
                          for name, led in self._ledgers.items()
                          if name not in self._parked]
        GLOBAL_METRICS.set_gauge_family("grove_sweep_write_amp",
                                        amp_series)
        target = watch_lag_slo_s()
        lag_series: list[tuple[dict, float]] = []
        breach_series: list[tuple[dict, float]] = []
        for kind, stats in self._watch_lag_stats().items():
            lag_series.append(({"kind": kind}, stats["last_s"]))
            breach_series.append(({"kind": kind},
                                  1.0 if stats["last_s"] > target else 0.0))
        GLOBAL_METRICS.set_gauge_family("grove_informer_watch_lag_seconds",
                                        lag_series)
        GLOBAL_METRICS.set_gauge_family("grove_informer_watch_lag_breached",
                                        breach_series)

    def _watch_lag_stats(self) -> dict[str, dict]:
        informer_set = self._informers_ref() \
            if self._informers_ref is not None else None
        if informer_set is None:
            return {}
        stats: dict[str, dict] = {}
        for inf in informer_set.informers():
            snap = inf.lag_snapshot()
            if snap["events"]:
                stats[inf.KIND] = snap
        return stats

    def payload(self) -> dict:
        """The /debug/controlplane body (served by Client.debug_
        controlplane and its HTTP twin). Server-side "now" so renderers
        and assertions don't need a second clock."""
        target = watch_lag_slo_s()
        with self._lock:
            controllers = {}
            for name, led in self._ledgers.items():
                controllers[name] = {
                    "sweeps": led.sweeps,
                    "causes": dict(led.causes),
                    "wall_s": led.wall_s,
                    "lock_wait_s": led.lock_wait_s,
                    "store_write_s": led.store_write_s,
                    "compute_s": led.compute_s,
                    "write_calls": led.write_calls,
                    "changed": led.commits,
                    "noops": led.noops,
                    "conflicts": led.conflicts,
                    "fenced": led.fenced,
                    "scans": led.scans,
                    "verbs": dict(led.verb_calls),
                    "write_amp": led.total_amp(),
                    "recent_write_amp": led.recent_amp(),
                    "parked": name in self._parked,
                    "last": dict(led.last),
                }
            hot = sorted(self._hot.items(), key=lambda kv: kv[1][0],
                         reverse=True)[:10]
        watch_lag = {}
        for kind, stats in self._watch_lag_stats().items():
            watch_lag[kind] = {
                "events": stats["events"],
                "last_s": stats["last_s"],
                "max_s": stats["max_s"],
                "breached": stats["last_s"] > target,
            }
        wait_sum, wait_n = GLOBAL_METRICS.hist_totals(
            "grove_workqueue_wait_seconds")
        work_sum, work_n = GLOBAL_METRICS.hist_totals(
            "grove_workqueue_work_seconds")
        return {
            "now": time.time(),
            "enabled": enabled(),
            "write_obs_enabled": writeobs.enabled(),
            "slo_target_s": target,
            "controllers": controllers,
            "hot_objects": [
                {"controller": ctrl, "key": key, "write_calls": h[0],
                 "changed": h[1], "sweeps": h[2]}
                for (ctrl, key), h in hot],
            "watch_lag": watch_lag,
            "queue": {"wait_s": wait_sum, "waits": wait_n,
                      "work_s": work_sum, "works": work_n},
        }


# Cached pre-sorted label tuples (the writeobs idiom): cardinality is
# controllers x causes / controllers x verbs — small and bounded.
_SWEEP_LABELS: dict[tuple[str, str], tuple] = {}
_WRITE_LABELS: dict[tuple[str, str], tuple] = {}


def _sweep_labels(cause: str, controller: str) -> tuple:
    key = (cause, controller)
    labels = _SWEEP_LABELS.get(key)
    if labels is None:
        labels = _SWEEP_LABELS[key] = (("cause", cause),
                                       ("controller", controller))
    return labels


def _write_labels(controller: str, verb: str) -> tuple:
    key = (controller, verb)
    labels = _WRITE_LABELS.get(key)
    if labels is None:
        labels = _WRITE_LABELS[key] = (("controller", controller),
                                       ("verb", verb))
    return labels


@contextlib.contextmanager
def maybe_record(observer: SweepObserver | None, controller: str,
                 cause: str, key: str) -> Iterator[SweepSink | None]:
    """record() that tolerates an unmanaged controller (no observer) —
    the Controller._process call site stays one line either way."""
    if observer is None or not enabled():
        yield None
        return
    with observer.record(controller, cause, key) as sink:
        yield sink


def render_controlplane_status(payload: dict,
                               now: float | None = None,
                               max_write_amp: float =
                               DEFAULT_WRITE_AMP_THRESHOLD) -> list[str]:
    """grovectl controlplane-status lines (shared by CLI and tests —
    the render-beside-recorder house pattern). The hottest controller
    (largest sweep wall share) is starred."""
    now = payload.get("now", now or time.time())
    lines = ["control-plane observatory"
             + ("" if payload.get("enabled", True)
                else "  [GROVE_SWEEP_OBS=0 — ledger frozen]")]
    controllers = payload.get("controllers", {})
    hottest = max(controllers, key=lambda n: controllers[n]["wall_s"]) \
        if controllers else None
    lines.append(f"  controllers: {len(controllers)}  "
                 f"watch-lag SLO target: "
                 f"{payload.get('slo_target_s', 0.0):.3f}s")
    for name in sorted(controllers,
                       key=lambda n: -controllers[n]["wall_s"]):
        led = controllers[name]
        star = "*" if name == hottest else " "
        causes = ",".join(f"{c}:{n}" for c, n in sorted(
            led["causes"].items(), key=lambda kv: -kv[1])[:3])
        amp = led["recent_write_amp"]
        flag = "  AMP!" if amp > max_write_amp else ""
        parked = "  (parked)" if led.get("parked") else ""
        lines.append(
            f"{star} {name:<16} sweeps {led['sweeps']:>6}  "
            f"wall {led['wall_s']*1000.0:8.1f}ms "
            f"(lock {led['lock_wait_s']*1000.0:.1f} / store "
            f"{led['store_write_s']*1000.0:.1f} / compute "
            f"{led['compute_s']*1000.0:.1f})  "
            f"writes {led['write_calls']} calls / {led['changed']} "
            f"changed (amp {amp:.2f}){flag}  causes {causes}"
            f"{parked}")
    hot = payload.get("hot_objects", [])
    if hot:
        lines.append("  hottest objects:")
        for h in hot[:5]:
            lines.append(f"    {h['controller']} {h['key']}: "
                         f"{h['write_calls']} writes / {h['changed']} "
                         f"changed over {h['sweeps']} sweeps")
    for kind, wl in sorted(payload.get("watch_lag", {}).items()):
        verdict = "BREACH" if wl["breached"] else "ok"
        lines.append(f"  watch-lag {kind:<14} last "
                     f"{wl['last_s']*1000.0:8.3f}ms  max "
                     f"{wl['max_s']*1000.0:8.3f}ms  events "
                     f"{wl['events']:>7}  [{verdict}]")
    q = payload.get("queue", {})
    if q.get("works"):
        lines.append(f"  queue: wait {q['wait_s']:.3f}s over "
                     f"{q['waits']:.0f} pickups, work "
                     f"{q['work_s']:.3f}s over {q['works']:.0f} sweeps")
    return lines


def status_problems(payload: dict,
                    max_write_amp: float = DEFAULT_WRITE_AMP_THRESHOLD
                    ) -> list[str]:
    """The exit-1 predicate grovectl and the smoke share: watch-lag SLO
    breaches and write-amp above threshold, as human-readable strings
    (empty = exit 0)."""
    problems = []
    for kind, wl in payload.get("watch_lag", {}).items():
        if wl.get("breached"):
            problems.append(
                f"watch-lag SLO breached for {kind}: last event applied "
                f"{wl['last_s']:.3f}s stale (target "
                f"{payload.get('slo_target_s', 0.0):.3f}s)")
    for name, led in payload.get("controllers", {}).items():
        amp = led.get("recent_write_amp", 0.0)
        if amp > max_write_amp:
            problems.append(
                f"write amplification on {name}: {amp:.2f} writes per "
                f"changed object (threshold {max_write_amp:.2f})")
    return problems
