"""Stable pod-index assignment with hole reuse.

Role parity with reference internal/index/tracker.go:35-90: pods carry a
stable integer index (their TPU_WORKER_ID within the clique); when a pod
dies, its index is a hole that the replacement pod must reuse so worker
identity survives pod replacement.
"""

from __future__ import annotations


def available_indices(used: list[int], want: int) -> list[int]:
    """Return ``want`` smallest non-negative integers not in ``used``."""
    taken = set(used)
    out: list[int] = []
    i = 0
    while len(out) < want:
        if i not in taken:
            out.append(i)
        i += 1
    return out
