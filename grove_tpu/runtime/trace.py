"""End-to-end gang lifecycle tracing — an in-process flight recorder.

Dapper-style distributed tracing (Sigelman et al., 2010) with
OpenTelemetry-shaped span semantics, scoped to what a self-contained
control plane actually needs: no external collector, no wire protocol —
a bounded ring of finished spans plus per-trace lifecycle milestones,
good enough to answer "why did this gang take 4s to come up?" from a
live cluster.

How a trace forms:

- ``Store.create`` stamps every new object with a trace id annotation
  (``ANNOTATION_TRACE_ID``): inherited from the object's pre-stamped
  annotation (controllers copy parent → child, so the whole
  PodCliqueSet tree shares the root's id), else from the creating
  span's context (an EventRecorder write inside a reconcile), else
  minted fresh.
- Watch events carry the id into controller workqueues
  (``_DelayQueue`` trace hints); each reconcile runs inside a
  ``reconcile.<controller>`` span.
- The gang scheduler wraps planning + binding in ``sched.place`` /
  ``sched.bind`` spans; node agents record ``agent.start`` and
  ``agent.barrier_wait`` spans per pod.
- Lifecycle milestones (gang_created → scheduled → started → ready)
  feed the SLO histograms in runtime/metrics.py:
  ``grove_gang_time_to_scheduled_seconds``,
  ``grove_gang_time_to_ready_seconds``, and the per-phase
  ``grove_lifecycle_phase_seconds{phase=...}``.

Surfaces: ``GET /debug/traces`` (server.py, gated like
``/debug/profile``) and ``grovectl trace <kind>/<name>`` render the
span tree with per-phase durations and the critical path.

``GROVE_TRACE=0`` disables recording (ids are still stamped — they are
inert annotations and keep wire/persisted state shape-stable).
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import dataclasses
import os
import random
import threading
import time

# The ObjectMeta annotation carrying an object's trace id. Defined here
# (not api/constants.py) so the tracer stays importable from the store
# without touching the api package; api.meta.trace_id_of re-reads it.
ANNOTATION_TRACE_ID = "grove.tpu/trace-id"

# Ambient span context per thread/task: (trace_id, span_id). Workers
# set it for the duration of a reconcile so nested spans parent
# correctly and objects created inside inherit the trace.
_SPAN_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "grove_trace_span", default=None)

# Private RNG (same reasoning as api.meta's uid rng): ids are identity
# handles, not secrets, and tests reseeding the global random module
# must not repeat trace ids.
_id_rng = random.Random(random.SystemRandom().getrandbits(64))


def _new_id() -> str:
    return f"{_id_rng.getrandbits(64):016x}"


@dataclasses.dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: str
    name: str
    start: float
    end: float
    attrs: dict[str, str]
    error: str = ""

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = str(value)

    def set_error(self, message) -> None:
        self.error = str(message)


class _NullSpan:
    """No-op span handle for untraced/disabled paths (hot loops pay one
    falsy check, not a dataclass + ring append)."""

    __slots__ = ()

    def set_attr(self, key: str, value) -> None:
        pass

    def set_error(self, message) -> None:
        pass


_NULL_SPAN = _NullSpan()

# Lifecycle milestone phases in pipeline order. "created" is implicit
# (the trace start, recorded when the root object's id is minted).
MILESTONE_PHASES = ("gang_created", "scheduled", "started", "ready")


class Tracer:
    """Bounded in-process tracer: finished-span ring + trace starts +
    per-(trace, subject) lifecycle milestones. Thread-safe; all maps
    are capped so a long-lived control plane cannot leak."""

    SPAN_CAPACITY = 8192
    TRACE_CAPACITY = 4096

    def __init__(self, capacity: int = SPAN_CAPACITY) -> None:
        self._lock = threading.Lock()
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=capacity)
        self._trace_start: "collections.OrderedDict[str, float]" = \
            collections.OrderedDict()
        # (trace_id, subject) -> {phase: ts}; subject is "<ns>/<gang>".
        self._milestones: "collections.OrderedDict[tuple[str, str], dict[str, float]]" = \
            collections.OrderedDict()
        self.enabled = os.environ.get("GROVE_TRACE", "1") != "0"

    # ---- trace identity ----

    def mint(self, ts: float | None = None) -> str:
        """New trace id; records the trace's start time (the anchor the
        time-to-* milestones measure from)."""
        tid = _new_id()
        with self._lock:
            self._trace_start[tid] = time.time() if ts is None else ts
            while len(self._trace_start) > self.TRACE_CAPACITY:
                self._trace_start.popitem(last=False)
        return tid

    def ensure(self, meta) -> str:
        """Stamp ``meta`` with a trace id if it has none: the object's
        own annotation wins (parent → child copies), then the creating
        span's ambient context, then a fresh mint. Called by
        Store.create for every object."""
        tid = meta.annotations.get(ANNOTATION_TRACE_ID, "")
        if tid:
            # Pre-stamped (child of a traced parent, or a wire create
            # carrying its id across a server restart): make sure a
            # start anchor exists without displacing the parent's.
            with self._lock:
                self._trace_start.setdefault(
                    tid, meta.creation_timestamp or time.time())
            return tid
        ctx = _SPAN_CTX.get()
        if ctx is not None:
            tid = ctx[0]
        else:
            tid = self.mint(ts=meta.creation_timestamp or None)
        meta.annotations[ANNOTATION_TRACE_ID] = tid
        return tid

    @staticmethod
    def current() -> tuple[str, str] | None:
        """(trace_id, span_id) of the ambient span, or None."""
        return _SPAN_CTX.get()

    # ---- spans ----

    @contextlib.contextmanager
    def span(self, name: str, trace_id: str | None = None,
             attrs: dict[str, str] | None = None):
        """Record a span around the with-block. ``trace_id`` binds the
        span to a trace explicitly (workqueue hints, object
        annotations); without one the ambient context's trace is used,
        and with neither the span is a no-op — untraced work must not
        fill the ring with orphans."""
        ctx = _SPAN_CTX.get()
        tid = trace_id or (ctx[0] if ctx is not None else "")
        if not self.enabled or not tid:
            yield _NULL_SPAN
            return
        parent = ctx[1] if (ctx is not None and ctx[0] == tid) else ""
        sp = Span(trace_id=tid, span_id=_new_id(), parent_id=parent,
                  name=name, start=time.time(), end=0.0,
                  attrs={k: str(v) for k, v in (attrs or {}).items()})
        token = _SPAN_CTX.set((tid, sp.span_id))
        try:
            yield sp
        except BaseException as e:
            sp.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            _SPAN_CTX.reset(token)
            sp.end = time.time()
            with self._lock:
                self._spans.append(sp)

    def record_span(self, name: str, trace_id: str, start: float,
                    end: float, attrs: dict[str, str] | None = None,
                    parent_id: str = "") -> None:
        """Record a span measured out-of-band (e.g. a barrier wait whose
        start was observed passes ago)."""
        if not self.enabled or not trace_id:
            return
        sp = Span(trace_id=trace_id, span_id=_new_id(),
                  parent_id=parent_id, name=name, start=start, end=end,
                  attrs={k: str(v) for k, v in (attrs or {}).items()})
        with self._lock:
            self._spans.append(sp)

    # ---- lifecycle milestones → SLO histograms ----

    def note_created(self, obj, defer_observe: bool = False):
        """Milestone hook for Store.create: gang creation is the first
        per-gang milestone (the root object's create is the trace
        start, recorded by ensure/mint). With ``defer_observe`` the
        milestone itself is recorded NOW (so later milestones — a
        scheduler binding the gang off the ADDED event — see
        gang_created already present) and the returned callable
        carries only the hub observation, for the store to run after
        its lock drops (the hub lock is held across /metrics renders;
        grove_tpu/analysis/lockdep.py convicted the in-lock call)."""
        if obj.KIND != "PodGang":
            return None
        tid = obj.meta.annotations.get(ANNOTATION_TRACE_ID, "")
        return self.milestone(tid,
                              f"{obj.meta.namespace}/{obj.meta.name}",
                              "gang_created",
                              ts=obj.meta.creation_timestamp,
                              defer_observe=defer_observe)

    def milestone(self, trace_id: str, subject: str, phase: str,
                  ts: float | None = None,
                  defer_observe: bool = False):
        """First-write-wins milestone for (trace, subject). Reaching a
        milestone observes the SLO histograms for the phase it closes;
        repeats (condition flapping, re-reconciles) are ignored so each
        gang contributes exactly one observation per phase. With
        ``defer_observe`` the milestone is recorded but the histogram
        observation is returned as a callable for the caller to run
        once it holds no locks (else None when nothing to observe)."""
        if not self.enabled or not trace_id:
            return None
        ts = time.time() if ts is None else ts
        with self._lock:
            key = (trace_id, subject)
            m = self._milestones.get(key)
            if m is None:
                m = self._milestones[key] = {}
                while len(self._milestones) > self.TRACE_CAPACITY:
                    self._milestones.popitem(last=False)
            if phase in m:
                return None
            m[phase] = ts
            # Anchor: trace mint time; a trace whose start was lost
            # (ring eviction, restart) falls back to its first
            # milestone so phase deltas stay right even when the
            # absolute time-to-* is unmeasurable.
            t0 = self._trace_start.get(trace_id,
                                       m.get("gang_created", ts))
            snapshot = dict(m)
        if defer_observe:
            return lambda: self._observe(phase, snapshot, t0, ts)
        self._observe(phase, snapshot, t0, ts)
        return None

    @staticmethod
    def _observe(phase: str, m: dict[str, float], t0: float,
                 ts: float) -> None:
        from grove_tpu.runtime.metrics import GLOBAL_METRICS

        def phase_obs(name: str, since: float) -> None:
            GLOBAL_METRICS.observe("grove_lifecycle_phase_seconds",
                                   max(0.0, ts - since), phase=name)

        if phase == "gang_created":
            phase_obs("create_to_gang", t0)
        elif phase == "scheduled":
            phase_obs("gang_to_scheduled", m.get("gang_created", t0))
            GLOBAL_METRICS.observe("grove_gang_time_to_scheduled_seconds",
                                   max(0.0, ts - t0))
        elif phase == "started":
            phase_obs("scheduled_to_started", m.get("scheduled", t0))
        elif phase == "ready":
            phase_obs("started_to_ready",
                      m.get("started", m.get("scheduled", t0)))
            GLOBAL_METRICS.observe("grove_gang_time_to_ready_seconds",
                                   max(0.0, ts - t0))

    # ---- export / inspection ----

    def export(self, trace_id: str | None = None) -> dict:
        """JSON-shaped dump for /debug/traces: spans (oldest first),
        milestones, and trace start anchors — optionally filtered to
        one trace."""
        with self._lock:
            spans = [dataclasses.asdict(s) for s in self._spans
                     if trace_id is None or s.trace_id == trace_id]
            milestones = [
                {"trace_id": tid, "subject": subject,
                 "phases": dict(phases)}
                for (tid, subject), phases in self._milestones.items()
                if trace_id is None or tid == trace_id]
            starts = {tid: ts for tid, ts in self._trace_start.items()
                      if trace_id is None or tid == trace_id}
        return {"spans": spans, "milestones": milestones,
                "starts": starts}

    def reset(self) -> None:
        """Drop all recorded state (test isolation)."""
        with self._lock:
            self._spans.clear()
            self._trace_start.clear()
            self._milestones.clear()


def critical_path(spans: list[dict]) -> list[str]:
    """Span ids on the chain from a root to the latest-finishing span —
    the path that bounded the trace's wall time. Operates on the
    dict shape ``Tracer.export`` (and the wire endpoint) returns."""
    if not spans:
        return []
    by_id = {s["span_id"]: s for s in spans}
    cur = max(spans, key=lambda s: s["end"])
    path: list[str] = []
    while cur is not None and cur["span_id"] not in path:
        path.append(cur["span_id"])
        cur = by_id.get(cur["parent_id"])
    return list(reversed(path))


GLOBAL_TRACER = Tracer()
