"""Controller runtime package.

Import submodules directly (grove_tpu.runtime.controller, .manager, ...);
this __init__ re-exports only leaf helpers to avoid import cycles with
the store (store raises runtime.errors; controller/manager consume the
store).
"""

from grove_tpu.runtime.errors import (
    AlreadyExistsError,
    ConflictError,
    GroveError,
    NotFoundError,
)
from grove_tpu.runtime.flow import StepResult

__all__ = [
    "AlreadyExistsError",
    "ConflictError",
    "GroveError",
    "NotFoundError",
    "StepResult",
]
