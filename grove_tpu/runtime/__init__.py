from grove_tpu.runtime.errors import (
    AlreadyExistsError,
    ConflictError,
    GroveError,
    NotFoundError,
)
from grove_tpu.runtime.flow import StepResult
from grove_tpu.runtime.controller import Controller, Request
from grove_tpu.runtime.manager import Manager

__all__ = [
    "AlreadyExistsError",
    "ConflictError",
    "GroveError",
    "NotFoundError",
    "StepResult",
    "Controller",
    "Request",
    "Manager",
]
