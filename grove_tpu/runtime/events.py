"""Event recording — the Kubernetes Events analog.

The reference records events through controller-runtime recorders (e.g.
scheduler capability events, volcano/backend.go:125). Here events are
first-class store objects (kind Event) with count-deduplication, so
`grovectl` and tests can surface why something is stuck.
"""

from __future__ import annotations

import dataclasses
import time

from grove_tpu.api.meta import ObjectMeta, new_meta
from grove_tpu.runtime.errors import ConflictError, GroveError, NotFoundError


@dataclasses.dataclass
class Event:
    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_name: str = ""
    type: str = "Normal"          # Normal | Warning
    reason: str = ""
    message: str = ""
    count: int = 1
    first_seen: float = 0.0
    last_seen: float = 0.0

    KIND = "Event"


class EventRecorder:
    def __init__(self, client, component: str, min_interval: float = 5.0):
        self.client = client
        self.component = component
        # Repeat-suppression window: a hot loop re-reporting the same
        # condition must not turn into a store write storm.
        self.min_interval = min_interval

    def event(self, obj, etype: str, reason: str, message: str,
              key: str = "") -> int:
        """Record (or bump) an event for ``obj``. Never raises.

        ``key`` disambiguates parallel subjects under one reason (e.g.
        per-replica gang terminations) so their histories don't overwrite
        each other. Rate limiting applies regardless of message content —
        varying messages must not bypass write-storm suppression.

        Returns the number of store writes performed (0 when suppressed
        or failed, 1 otherwise) — callers that track their own
        resource-version footprint (the placement snapshot) need an
        exact count of the rv bumps they caused.
        """
        name = f"{obj.meta.name}.{reason.lower()}"
        if key:
            name += f".{key}"
        ns = obj.meta.namespace
        now = time.time()
        try:
            try:
                cur = self.client.get(Event, name, ns)
                if now - cur.last_seen < self.min_interval:
                    return 0
                cur.count += 1
                cur.last_seen = now
                cur.message = message
                # Carry the CURRENT type through: a condition that
                # escalates Normal → Warning under the same reason must
                # surface as Warning on the bump, not keep the stale
                # type forever.
                cur.type = etype
                self.client.update(cur)
            except NotFoundError:
                ev = Event(
                    meta=new_meta(name, namespace=ns,
                                  labels={"component": self.component}),
                    involved_kind=obj.KIND, involved_name=obj.meta.name,
                    type=etype, reason=reason, message=message,
                    first_seen=now, last_seen=now)
                self.client.create(ev)
            return 1
        except (ConflictError, GroveError):
            return 0  # events are best-effort


def events_for(client, kind: str, name: str,
               namespace: str = "default") -> list[Event]:
    return [e for e in client.list(Event, namespace)
            if e.involved_kind == kind and e.involved_name == name]
