"""Reconcile-flow plumbing: step results and short-circuiting.

Role parity with reference internal/controller/common/flow.go
(ReconcileStepResult / ShortCircuitReconcileFlow): reconcilers are a
sequence of steps; each step either continues, completes the flow, or
requeues (with or without an error).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class StepResult:
    done: bool = False                  # stop the flow (success)
    requeue_after: Optional[float] = None
    error: Optional[Exception] = None

    CONTINUE: "StepResult" = None  # type: ignore[assignment]

    @staticmethod
    def ok() -> "StepResult":
        return StepResult()

    @staticmethod
    def finished() -> "StepResult":
        return StepResult(done=True)

    @staticmethod
    def requeue(after: float) -> "StepResult":
        return StepResult(done=True, requeue_after=after)

    @staticmethod
    def fail(err: Exception, requeue_after: float | None = None) -> "StepResult":
        return StepResult(done=True, error=err, requeue_after=requeue_after)

    @property
    def short_circuits(self) -> bool:
        return self.done or self.error is not None


StepResult.CONTINUE = StepResult()


def run_steps(*steps) -> StepResult:
    """Run callables returning StepResult until one short-circuits."""
    for step in steps:
        result = step()
        if result is not None and result.short_circuits:
            return result
    return StepResult.finished()
