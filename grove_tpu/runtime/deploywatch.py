"""Deploy observatory — per-PodCliqueSet rollout progress recording.

The reference's scale gate is a 1000-pod PCS deploy reaching Available
inside a 10-minute budget (SURVEY.md §6, scale_test.go). The lifecycle
tracer (runtime/trace.py) answers that question per GANG; this module
answers it per DEPLOY: one record per PodCliqueSet tracking how many
pods have been created/scheduled/started/become-ready over time, how
many store writes and conflicts the deploy consumed (write
amplification: writes per pod deployed), and how the control plane's
time split between queue waiting and reconcile work.

Feed: a store watch over PodCliqueSet/Pod/PodGang events, applied by a
dedicated observer thread (the same event stream the informer caches
consume — a deploy storm outruns the bounded replay ring between
scrapes, so the recorder must be push-fed, not pull-on-read). Pods map
to their PCS through the standard ``LABEL_PCS_NAME`` label.

When a PCS reaches Available, its milestone ladder is frozen and each
phase observed ONCE into ``grove_deploy_duration_seconds{phase}``
(first_pod → pods_created → scheduled → started → ready → available,
all measured from the PCS create) — the deploy-budget histogram a
deployed alert watches, pinned to the same LIFECYCLE_BUCKETS the gang
SLOs use.

Surfaces:
- ``GET /debug/deploy/<ns>/<name>`` (server.py; plain status-shaped
  data, so read-gated like /debug/placement, not profiling-gated);
- ``Client.debug_deploy`` / ``HttpClient.debug_deploy`` twins (one
  payload shape in-process and over the wire);
- ``grovectl deploy-status <name>`` renders it (render_deploy_status).

Write/conflict accounting reads the write-path telemetry counters
(store/writeobs.py) as whole-hub snapshots at deploy start vs
Available — store-global, so overlapping deploys share the delta; with
``GROVE_WRITE_OBS=0`` the write columns read zero. Records are bounded
(RECORD_CAPACITY, oldest evicted) and survive PCS deletion so a
completed deploy stays inspectable.
"""

from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import Any

from grove_tpu.api import constants as c
from grove_tpu.api.meta import is_condition_true
from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.metrics import GLOBAL_METRICS

# Stages a pod moves through during a deploy, in pipeline order.
POD_STAGES = ("created", "scheduled", "started", "ready")

# Milestone phases observed into grove_deploy_duration_seconds.
DEPLOY_PHASES = ("first_pod", "pods_created", "scheduled", "started",
                 "ready", "available")

# store (weakly) -> its observer, so the in-process Client can resolve
# debug_deploy without holding a manager reference.
_OBSERVERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def observer_for(store) -> "DeployObserver | None":
    return _OBSERVERS.get(store)


class _HubSnapshot:
    """Point-in-time totals of the write/queue series a deploy
    consumes; two of these subtract into the deploy's consumption."""

    __slots__ = ("writes", "conflicts", "noops", "wait_s", "work_s")

    def __init__(self) -> None:
        self.writes = GLOBAL_METRICS.counter_total(
            "grove_store_writes_total")
        self.conflicts = GLOBAL_METRICS.counter_total(
            "grove_store_conflicts_total")
        self.noops = GLOBAL_METRICS.counter_total(
            "grove_store_noop_writes_total")
        self.wait_s = GLOBAL_METRICS.hist_totals(
            "grove_workqueue_wait_seconds")[0]
        self.work_s = GLOBAL_METRICS.hist_totals(
            "grove_workqueue_work_seconds")[0]

    def delta(self, since: "_HubSnapshot") -> dict:
        return {
            "writes": round(self.writes - since.writes),
            "conflicts": round(self.conflicts - since.conflicts),
            "noop_writes": round(self.noops - since.noops),
            "queue_wait_s": round(self.wait_s - since.wait_s, 6),
            "work_s": round(self.work_s - since.work_s, 6),
        }


class DeployRecord:
    """One PodCliqueSet's deploy, from create to Available."""

    __slots__ = ("namespace", "name", "created_at", "available_at",
                 "deleted", "pods", "gangs", "start_snapshot",
                 "final_usage", "milestones")

    def __init__(self, namespace: str, name: str, created_at: float,
                 snapshot: _HubSnapshot):
        self.namespace = namespace
        self.name = name
        self.created_at = created_at
        self.available_at: float | None = None
        self.deleted = False
        # pod name -> {stage: first-reach ts}
        self.pods: dict[str, dict[str, float]] = {}
        # gang name -> scheduled?
        self.gangs: dict[str, bool] = {}
        # Built by the caller OUTSIDE the observer lock (hub-lock work
        # must not run under it — see DeployObserver._apply).
        self.start_snapshot = snapshot
        self.final_usage: dict | None = None   # frozen at Available
        self.milestones: dict[str, float] = {}


class DeployObserver:
    """Watch-fed per-PCS deploy recorder (a manager runnable)."""

    RECORD_CAPACITY = 64

    def __init__(self, store) -> None:
        # Weak store ref: _OBSERVERS is weakly KEYED by the store, and
        # a WeakKeyDictionary strongly references its VALUES — a strong
        # store ref here would keep the key alive through the value and
        # leak every discarded Manager's store + records for process
        # lifetime (the weakref-doc caveat).
        self._store_ref = weakref.ref(store)
        from grove_tpu.analysis import lockdep
        self._lock = lockdep.maybe_wrap(threading.Lock(), "deploy-observer")
        self._records: "collections.OrderedDict[tuple[str, str], DeployRecord]" = \
            collections.OrderedDict()
        # Keys of records that can still finalize (not yet Available,
        # not deleted, not evicted). Read by _apply BEFORE the observer
        # lock to decide whether a PCS event needs a hub snapshot —
        # only the event thread touches it, so no extra locking.
        self._pending: set[tuple[str, str]] = set()
        self._watcher = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.log = get_logger("deploywatch")

    # ---- lifecycle (manager runnable contract) ----

    def start(self) -> None:
        store = self._store_ref()
        if store is None:
            return
        # Registration happens on START, not construction: a second
        # Manager merely CONSTRUCTED over the same store (never
        # started) must not shadow the running observer's records —
        # observer_for resolves to whoever actually watches. The entry
        # survives stop() so completed deploys stay inspectable.
        _OBSERVERS[store] = self
        self._stop.clear()
        self._watcher = store.watch(
            kinds={"PodCliqueSet", "Pod", "PodGang"})
        self._thread = threading.Thread(target=self._loop,
                                        name="deploy-observer", daemon=True)
        self._thread.start()

    def request_stop(self) -> None:
        """Signal-only phase of the manager's two-phase shutdown."""
        self._stop.set()
        if self._watcher is not None:
            self._watcher.close()

    def stop(self) -> None:
        self.request_stop()
        # Join before a possible restart: _apply's unlocked _pending
        # read assumes ONE event thread; a stop->start inside the old
        # thread's poll window would otherwise leave two running.
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            event = self._watcher.poll(timeout=0.2)
            if event is None:
                continue
            try:
                self._apply(event)
            except Exception:  # noqa: BLE001 - observer must not die
                self.log.exception("deploy observer dropped an event")

    # ---- event application ----

    def _apply(self, event) -> None:
        obj = event.obj
        ts = event.ts or time.time()
        kind = obj.KIND
        etype = event.type.value
        # Lock-ordering discipline (same as payload()): hub-locked work
        # never runs under the observer lock — a /metrics render holds
        # the hub lock across the full exposition, and blocking the
        # event thread on it would back events up in the watcher queue.
        # So the snapshot a PCS event might need is built BEFORE the
        # lock, and finalize observations are emitted AFTER it. Only
        # the two consuming transitions pay for one (ADDED seeds a
        # record; a MODIFIED that can actually finalize a still-pending
        # record satisfies the availability predicate) — NOT every
        # status write in the event stream of a PCS that is already
        # Available, where the predicate stays true forever.
        snap = None
        if kind == "PodCliqueSet":
            key = (obj.meta.namespace, obj.meta.name)
            if etype == "ADDED" or (
                    etype == "MODIFIED" and key in self._pending
                    and obj.spec.replicas > 0
                    and obj.status.available_replicas
                    >= obj.spec.replicas):
                snap = _HubSnapshot()
        observations: list[tuple[str, float]] = []
        with self._lock:
            if kind == "PodCliqueSet":
                self._apply_pcs(etype, obj, ts, snap, observations)
            elif kind == "Pod":
                self._apply_pod(event.type.value, obj, ts)
            elif kind == "PodGang":
                self._apply_gang(event.type.value, obj, ts)
        for phase, seconds in observations:
            GLOBAL_METRICS.observe("grove_deploy_duration_seconds",
                                   seconds, phase=phase)

    def _apply_pcs(self, etype: str, obj: Any, ts: float,
                   snap: "_HubSnapshot | None",
                   observations: list[tuple[str, float]]) -> None:
        # ``snap`` is non-None exactly on the paths that consume it
        # (ADDED, and a MODIFIED passing the availability predicate) —
        # _apply's pre-lock gate mirrors the conditions here.
        key = (obj.meta.namespace, obj.meta.name)
        if etype == "ADDED":
            # A re-created PCS starts a fresh deploy record.
            self._records[key] = DeployRecord(
                obj.meta.namespace, obj.meta.name,
                obj.meta.creation_timestamp or ts, snap)
            self._records.move_to_end(key)
            self._pending.add(key)
            while len(self._records) > self.RECORD_CAPACITY:
                evicted, _ = self._records.popitem(last=False)
                self._pending.discard(evicted)
            return
        rec = self._records.get(key)
        if rec is None:
            return
        if etype == "DELETED":
            # A deleted PCS emits no further events, so an unfinalized
            # record can never finalize — stop paying for snapshots.
            rec.deleted = True
            self._pending.discard(key)
            return
        if rec.available_at is None and obj.spec.replicas > 0 \
                and obj.status.available_replicas >= obj.spec.replicas:
            self._pending.discard(key)
            self._finalize(rec, ts, snap, observations)

    def _record_for(self, obj: Any) -> DeployRecord | None:
        pcs = obj.meta.labels.get(c.LABEL_PCS_NAME)
        if not pcs:
            return None
        return self._records.get((obj.meta.namespace, pcs))

    def _apply_pod(self, etype: str, obj: Any, ts: float) -> None:
        rec = self._record_for(obj)
        if rec is None or etype == "DELETED":
            return
        stages = rec.pods.setdefault(obj.meta.name, {})
        # First-write-wins per stage: re-reconciles and condition
        # flapping must not move a milestone backwards (or forwards).
        if "created" not in stages:
            stages["created"] = obj.meta.creation_timestamp or ts
        st = obj.status
        if st.node_name and "scheduled" not in stages:
            stages["scheduled"] = ts
        phase = getattr(st.phase, "value", st.phase)
        if phase in ("Running", "Succeeded") and "started" not in stages:
            stages["started"] = ts
        if "ready" not in stages and is_condition_true(st.conditions,
                                                       c.COND_READY):
            stages["ready"] = ts

    def _apply_gang(self, etype: str, obj: Any, ts: float) -> None:
        rec = self._record_for(obj)
        if rec is None or etype == "DELETED":
            return
        scheduled = is_condition_true(obj.status.conditions,
                                      c.COND_SCHEDULED)
        rec.gangs[obj.meta.name] = rec.gangs.get(obj.meta.name, False) \
            or scheduled

    def _finalize(self, rec: DeployRecord, ts: float,
                  snap: _HubSnapshot,
                  observations: list[tuple[str, float]]) -> None:
        """Freeze the deploy at Available: milestone ladder collected
        into ``observations`` (the caller observes them into the phase
        histogram outside the observer lock), write/queue consumption
        pinned from the pre-lock snapshot."""
        rec.available_at = ts
        rec.final_usage = snap.delta(rec.start_snapshot)
        t0 = rec.created_at
        created = [s["created"] for s in rec.pods.values()
                   if "created" in s]
        if created:
            rec.milestones["first_pod"] = min(created)
            rec.milestones["pods_created"] = max(created)
        for stage, phase in (("scheduled", "scheduled"),
                             ("started", "started"), ("ready", "ready")):
            hit = [s[stage] for s in rec.pods.values() if stage in s]
            if hit:
                rec.milestones[phase] = max(hit)
        rec.milestones["available"] = ts
        for phase in DEPLOY_PHASES:
            if phase in rec.milestones:
                observations.append(
                    (phase, max(0.0, rec.milestones[phase] - t0)))

    # ---- read surface ----

    def payload(self, namespace: str, name: str) -> dict | None:
        """The /debug/deploy payload for one PCS, or None when no
        record exists (PCS created before the observer started, or
        evicted). In-progress deploys report live consumption deltas;
        completed ones report the frozen numbers."""
        # Hub-snapshot discipline, poller flavor: (a) only an
        # IN-PROGRESS record needs a live snapshot — a finalized one
        # serves its frozen usage and a missing one serves nothing, so
        # polling a completed deploy must not pay five whole-hub scans
        # per request; (b) when one is needed it is built BETWEEN lock
        # round trips, never under the observer lock, which the event-
        # apply thread needs (events back up in the watcher queue
        # otherwise). Slightly stale against the record is fine —
        # in-progress numbers are a moving estimate.
        with self._lock:
            rec = self._records.get((namespace, name))
            need_live = rec is not None and rec.final_usage is None
        if rec is None:
            return None
        live = _HubSnapshot() if need_live else None
        with self._lock:
            # final_usage may have been frozen between the two lock
            # sections (one wasted snapshot); it is never un-frozen.
            usage = rec.final_usage if rec.final_usage is not None \
                else live.delta(rec.start_snapshot)
            counts = {stage: sum(1 for s in rec.pods.values()
                                 if stage in s)
                      for stage in POD_STAGES}
            pods_created = counts["created"]
            return {
                "kind": "PodCliqueSet",
                "namespace": rec.namespace,
                "name": rec.name,
                # Server-side clock for "in progress for Ns": created_at
                # is a server stamp, so a remote grovectl must not
                # subtract it from its own (possibly skewed) clock.
                "now": time.time(),
                "created_at": rec.created_at,
                "available_at": rec.available_at,
                "deleted": rec.deleted,
                "pods": counts,
                "gangs": {"total": len(rec.gangs),
                          "scheduled": sum(
                              1 for v in rec.gangs.values() if v)},
                "milestones": dict(rec.milestones),
                "writes": {
                    **usage,
                    "writes_per_pod": round(
                        usage["writes"] / pods_created, 2)
                    if pods_created else 0.0,
                },
            }


def render_deploy_status(payload: dict, now: float) -> list[str]:
    """Human rendering of a /debug/deploy payload — the `grovectl
    deploy-status` body (kept beside the recorder so the CLI and tests
    share one renderer, the render_explain precedent)."""
    t0 = payload.get("created_at", now)
    # Prefer the server's clock for the in-progress age: created_at is
    # a server stamp, and a skewed client clock would render negative
    # (or inflated) durations. `now` stays the fallback for payloads
    # from older servers.
    now = payload.get("now", now)
    avail = payload.get("available_at")
    name = f"{payload.get('kind', 'PodCliqueSet')}/{payload.get('name')}"
    out = []
    if avail:
        head = f"{name}: AVAILABLE after {avail - t0:.2f}s"
    else:
        head = f"{name}: deploy IN PROGRESS for {now - t0:.1f}s"
    if payload.get("deleted"):
        head += "  (object since deleted)"
    out.append(head)
    pods = payload.get("pods", {})
    out.append("  pods:  " + "  ".join(
        f"{stage} {pods.get(stage, 0)}" for stage in POD_STAGES))
    gangs = payload.get("gangs", {})
    out.append(f"  gangs: {gangs.get('scheduled', 0)}"
               f"/{gangs.get('total', 0)} scheduled")
    miles = payload.get("milestones", {})
    if miles:
        out.append("  milestones: " + "  ".join(
            f"{phase} +{miles[phase] - t0:.2f}s"
            for phase in DEPLOY_PHASES if phase in miles))
    w = payload.get("writes", {})
    out.append(
        f"  writes: {w.get('writes', 0)} committed, "
        f"{w.get('conflicts', 0)} conflicts, "
        f"{w.get('noop_writes', 0)} suppressed no-ops"
        f"  ->  {w.get('writes_per_pod', 0.0):.1f} writes/pod")
    wait, work = w.get("queue_wait_s", 0.0), w.get("work_s", 0.0)
    total = wait + work
    out.append(
        f"  queue: {wait:.2f}s waiting vs {work:.2f}s reconciling"
        + (f"  ({100 * wait / total:.0f}% wait)" if total > 0 else ""))
    return out
