"""Managed TLS certificates for the HTTP API server — the C6 analog.

The reference gates its webhook server behind TLS with either
self-provisioned + rotated certs or a BYO secret
(operator/internal/controller/cert/cert.go:50-117, modes at
api/config/v1alpha1/types.go:230). grove-tpu's standalone control plane
ships its own HTTP API instead of webhooks, so the same machinery lands
here: a ``CertManager`` that either

- **self-managed** (default): generates a long-lived CA and a short-lived
  leaf server certificate into ``cert_dir`` (``ca.crt``, ``ca.key``,
  ``tls.crt``, ``tls.key``), re-issuing the leaf when it enters the
  rotation window. Clients pin ``ca.crt`` once; rotation never changes it
  (the CA lives 10x the leaf validity).
- **byo**: serves operator-supplied ``cert_file``/``key_file`` unmodified,
  after checking the pair actually matches and has not expired — the two
  failure modes that otherwise surface as undebuggable handshake errors.

Rotation is applied by reloading the chain into the live
``ssl.SSLContext`` — new handshakes pick up the new leaf; established
connections are untouched.
"""

from __future__ import annotations

import dataclasses
import datetime
import ipaddress
import os
import ssl
import threading

from grove_tpu.runtime.errors import ValidationError

_DAY = datetime.timedelta(days=1)


@dataclasses.dataclass
class CertPaths:
    cert_file: str
    key_file: str
    ca_file: str = ""


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def _new_key():
    from cryptography.hazmat.primitives.asymmetric import ec

    return ec.generate_private_key(ec.SECP256R1())


def _key_pem(key) -> bytes:
    from cryptography.hazmat.primitives import serialization

    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())


def _name(cn: str):
    from cryptography import x509
    from cryptography.x509.oid import NameOID

    return x509.Name([
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, "grove-tpu"),
        x509.NameAttribute(NameOID.COMMON_NAME, cn),
    ])


def _san_entries(sans: list[str]):
    from cryptography import x509

    entries = []
    for san in sans:
        try:
            entries.append(x509.IPAddress(ipaddress.ip_address(san)))
        except ValueError:
            entries.append(x509.DNSName(san))
    return entries


def generate_ca(validity: datetime.timedelta):
    """Self-signed CA (key, cert)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes

    key = _new_key()
    now = _now()
    cert = (
        x509.CertificateBuilder()
        .subject_name(_name("grove-tpu-ca"))
        .issuer_name(_name("grove-tpu-ca"))
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - _DAY)           # clock-skew slack
        .not_valid_after(now + validity)
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .add_extension(
            x509.KeyUsage(digital_signature=True, key_cert_sign=True,
                          crl_sign=True, content_commitment=False,
                          key_encipherment=False, data_encipherment=False,
                          key_agreement=False, encipher_only=False,
                          decipher_only=False),
            critical=True)
        .sign(key, hashes.SHA256())
    )
    return key, cert


def issue_leaf(ca_key, ca_cert, sans: list[str],
               validity: datetime.timedelta):
    """Server leaf certificate signed by the CA."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.x509.oid import ExtendedKeyUsageOID

    key = _new_key()
    now = _now()
    cert = (
        x509.CertificateBuilder()
        .subject_name(_name("grove-tpu-api"))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - _DAY)
        .not_valid_after(now + validity)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                       critical=True)
        .add_extension(x509.SubjectAlternativeName(_san_entries(sans)),
                       critical=False)
        .add_extension(
            x509.ExtendedKeyUsage([ExtendedKeyUsageOID.SERVER_AUTH]),
            critical=False)
        .sign(ca_key, hashes.SHA256())
    )
    return key, cert


def _load_cert(path: str):
    from cryptography import x509

    with open(path, "rb") as f:
        return x509.load_pem_x509_certificate(f.read())


def _load_key(path: str):
    from cryptography.hazmat.primitives import serialization

    with open(path, "rb") as f:
        return serialization.load_pem_private_key(f.read(), password=None)


def _pair_matches(cert, key) -> bool:
    from cryptography.hazmat.primitives import serialization

    pub = serialization.Encoding.PEM, serialization.PublicFormat.SubjectPublicKeyInfo
    return (cert.public_key().public_bytes(*pub)
            == key.public_key().public_bytes(*pub))


def _write_private(path: str, data: bytes) -> None:
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(data)


class CertManager:
    """Provision, validate, and rotate the API server's TLS material.

    ``ensure()`` is idempotent and cheap when nothing needs doing; the
    server calls it at startup and on a timer (``maybe_rotate``) so a
    long-lived daemon never serves an expired leaf.
    """

    def __init__(self, tls_config):
        self.cfg = tls_config
        self._lock = threading.Lock()
        self._context: ssl.SSLContext | None = None

    # -- provisioning -----------------------------------------------------

    def ensure(self) -> CertPaths:
        if self.cfg.mode == "byo":
            return self._ensure_byo()
        return self._ensure_self_managed()

    def _ensure_byo(self) -> CertPaths:
        cfg = self.cfg
        if not cfg.cert_file or not cfg.key_file:
            raise ValidationError(
                "server_tls mode 'byo' requires cert_file and key_file")
        for p in (cfg.cert_file, cfg.key_file):
            if not os.path.exists(p):
                raise ValidationError(f"server_tls: {p!r} does not exist")
        cert = _load_cert(cfg.cert_file)
        if not _pair_matches(cert, _load_key(cfg.key_file)):
            raise ValidationError(
                f"server_tls: key {cfg.key_file!r} does not match "
                f"certificate {cfg.cert_file!r}")
        if cert.not_valid_after_utc <= _now():
            raise ValidationError(
                f"server_tls: certificate {cfg.cert_file!r} expired "
                f"{cert.not_valid_after_utc.isoformat()}")
        return CertPaths(cfg.cert_file, cfg.key_file, cfg.ca_file)

    def _paths(self) -> CertPaths:
        # Absolute: these paths are handed to other processes (pod env,
        # printed export hints) whose cwd is not the daemon's.
        d = os.path.abspath(self.cfg.cert_dir)
        return CertPaths(os.path.join(d, "tls.crt"),
                         os.path.join(d, "tls.key"),
                         os.path.join(d, "ca.crt"))

    def _ensure_self_managed(self) -> CertPaths:
        with self._lock:
            paths = self._paths()
            d = self.cfg.cert_dir
            os.makedirs(d, exist_ok=True)
            ca_key_path = os.path.join(d, "ca.key")
            validity = datetime.timedelta(days=self.cfg.validity_days)

            ca_ok = os.path.exists(paths.ca_file) and os.path.exists(ca_key_path)
            if ca_ok:
                ca_cert = _load_cert(paths.ca_file)
                # Re-root ONLY once the CA has actually expired (every
                # pinned client is already broken at that point).
                # Replacing a still-valid trust anchor behind running
                # agents' backs would cut off the whole fleet — rotating
                # the CA early is a deliberate operator action (remove
                # cert_dir, redistribute ca.crt).
                ca_ok = ca_cert.not_valid_after_utc > _now()
            if not ca_ok:
                ca_key, ca_cert = generate_ca(10 * validity)
                _write_private(ca_key_path, _key_pem(ca_key))
                with open(paths.ca_file, "wb") as f:
                    f.write(_cert_pem(ca_cert))

            if self._leaf_needs_issue(paths, ca_cert):
                ca_key = _load_key(ca_key_path)
                # Leaf lifetime never outlives the CA that signed it.
                leaf_validity = min(validity,
                                    ca_cert.not_valid_after_utc - _now())
                key, cert = issue_leaf(ca_key, ca_cert,
                                       list(self.cfg.sans), leaf_validity)
                _write_private(paths.key_file, _key_pem(key))
                with open(paths.cert_file, "wb") as f:
                    f.write(_cert_pem(cert))
            return paths

    def _leaf_needs_issue(self, paths: CertPaths, ca_cert) -> bool:
        if not (os.path.exists(paths.cert_file)
                and os.path.exists(paths.key_file)):
            return True
        cert = _load_cert(paths.cert_file)
        if cert.issuer != ca_cert.subject:
            return True                      # CA was re-rooted
        # A leaf that no longer covers every configured SAN must be
        # re-issued immediately: restarting serve with a new --host or
        # --tls-san against an existing cert_dir would otherwise keep
        # serving the old leaf, and clients dialing the new name fail
        # hostname verification until the rotation window.
        from cryptography import x509

        try:
            san_ext = cert.extensions.get_extension_for_class(
                x509.SubjectAlternativeName).value
            have = ({str(n) for n in san_ext.get_values_for_type(x509.DNSName)}
                    | {str(ip) for ip in
                       san_ext.get_values_for_type(x509.IPAddress)})
        except x509.ExtensionNotFound:
            have = set()
        if not set(self.cfg.sans) <= have:
            return True
        total = cert.not_valid_after_utc - cert.not_valid_before_utc
        remaining = cert.not_valid_after_utc - _now()
        return remaining <= total * self.cfg.rotation_fraction

    # -- the live server context ------------------------------------------

    def server_context(self) -> ssl.SSLContext:
        paths = self.ensure()
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        ctx.load_cert_chain(paths.cert_file, paths.key_file)
        self._context = ctx
        return ctx

    def maybe_rotate(self) -> bool:
        """Rotate the leaf if due and reload it into the live context.
        Returns True when a rotation happened. BYO mode never rotates —
        the operator owns the files."""
        if self.cfg.mode == "byo" or self._context is None:
            return False
        paths = self._paths()
        ca_cert = _load_cert(paths.ca_file)
        if not self._leaf_needs_issue(paths, ca_cert):
            return False
        paths = self.ensure()
        self._context.load_cert_chain(paths.cert_file, paths.key_file)
        return True


def _cert_pem(cert) -> bytes:
    from cryptography.hazmat.primitives import serialization

    return cert.public_bytes(serialization.Encoding.PEM)
