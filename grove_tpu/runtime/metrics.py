"""Prometheus-style metrics for the control plane.

Role parity with the reference's controller-runtime metrics server
(config types.go:202-212): counters/gauges/histograms with labels,
rendered in the Prometheus text exposition format by ``render``. The
manager exposes ``Manager.metrics_text()``; a real deployment serves it
over HTTP.

Histograms are fixed-bucket (the controller-runtime reconcile-time /
workqueue-duration shape): cumulative ``_bucket{le=...}`` samples plus
``_sum``/``_count``, so a deployed control plane can alert on the same
p95 the scale harness asserts (``histogram_quantile`` over the exposed
buckets — see ``parse_histograms`` / ``quantile_from_buckets``).
"""

from __future__ import annotations

import math
import re
import threading
from collections import defaultdict

# Prometheus default duration buckets — what controller-runtime uses
# for reconcile time; upper bounds in seconds.
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0)

# Pinned buckets for gang lifecycle SLOs (time-to-scheduled /
# time-to-ready and the per-phase histogram): a CPU test cluster lands
# in the sub-second bands, a production fleet under contention can take
# minutes — the default duration buckets top out at 10s and would
# flatten every slow bring-up into +Inf.
LIFECYCLE_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                     30.0, 60.0, 120.0, 300.0)

# Pinned buckets for the gang pending-time histogram (first failed
# placement attempt -> successful schedule, observed once at schedule):
# a stuck gang is a minutes-to-hours phenomenon — capacity arriving,
# preemption, node recovery — so the tail extends to an hour where the
# lifecycle buckets stop at five minutes.
PENDING_BUCKETS = (1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0,
                   1800.0, 3600.0)

# Pinned buckets for the data-plane device-step histogram
# (serving/xprof.py): a tiny CPU test engine decodes in tens of
# microseconds to milliseconds per step, a real chip in low
# milliseconds, and a tunnelled/degraded relay can stretch one block
# dispatch past a second — the default duration buckets (5ms floor)
# would flatten the entire healthy band.
DEVICE_STEP_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3,
                       2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25,
                       0.5, 1.0, 2.5)

# Pinned buckets for XLA compile wall time: a tiny test graph builds in
# tens of milliseconds, a flagship decode graph in seconds, and a cold
# 70B-scale lowering over a slow relay in minutes.
COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0, 300.0)

# Pinned buckets for the store-lock wait/hold histograms: a healthy
# write's critical section is microseconds, contention under a deploy
# storm is milliseconds, and anything past 100ms means the global lock
# is the bottleneck — the default duration buckets (5ms floor) would
# flatten the entire healthy band into their first bucket.
LOCK_BUCKETS = (5e-6, 1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3,
                2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1)

# Pinned buckets for write-verb calls per reconcile sweep
# (runtime/sweepobs.py): a converged sweep issues 0-1 calls, a
# replica-create sweep a handful, and a 4096-pod fan-out sweep lands in
# the hundreds — counts, not seconds, so the duration defaults would be
# nonsense here.
SWEEP_WRITE_BUCKETS = (1.0, 2.0, 3.0, 5.0, 8.0, 16.0, 32.0, 64.0,
                       128.0, 256.0, 512.0)


class _Hist:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: tuple[float, ...]) -> None:
        # The bucket tuple is pinned at creation and rendering reads it
        # from here — re-describing a histogram with different buckets
        # after observations exist cannot silently misattribute counts
        # (describe_histogram raises instead).
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # +1 for +Inf
        self.sum = 0.0
        self.count = 0


class MetricsHub:
    def __init__(self) -> None:
        # Witnessed under GROVE_LOCKDEP=1: this lock is held across
        # every /metrics render, which is exactly why nothing may take
        # it while holding the store lock (grovelint's
        # hub-under-store-lock rule is the static twin of this edge).
        from grove_tpu.analysis import lockdep
        self._lock = lockdep.maybe_wrap(threading.Lock(), "hub")
        self._counters: dict[tuple[str, tuple], float] = defaultdict(float)
        self._gauges: dict[tuple[str, tuple], float] = {}
        self._hists: dict[tuple[str, tuple], _Hist] = {}
        self._buckets: dict[str, tuple[float, ...]] = {}
        self._help: dict[str, str] = {}

    def describe(self, name: str, help_text: str) -> None:
        self._help[name] = help_text

    def describe_histogram(self, name: str, help_text: str,
                           buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                           ) -> None:
        b = tuple(sorted(buckets))
        with self._lock:
            for (hname, _), h in self._hists.items():
                if hname == name and h.buckets != b:
                    raise ValueError(
                        f"histogram {name!r} already has observations "
                        f"with {len(h.buckets)} buckets; re-describing "
                        f"with {len(b)} would misattribute counts")
            self._help[name] = help_text
            self._buckets[name] = b

    # name/value are positional-only so "name" stays a legal LABEL key
    # (grove_autoscaler_conflicts_total{kind,name} — without the /,
    # a name= label kwarg collides with the metric-name parameter).
    def inc(self, name: str, value: float = 1.0, /, **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] += value

    def set(self, name: str, value: float, /, **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = value

    def set_gauge_family(self, name: str, series) -> None:
        """Replace gauge ``name``'s exported series wholesale: set
        every (labels_dict, value) pair in ``series`` and zero
        previously-exported label-sets missing from this update — a
        drained series must clear, not linger at its last value (the
        kube-state-metrics contract; callers don't each hand-roll
        last-exported-set bookkeeping)."""
        new = {tuple(sorted(labels.items())): float(v)
               for labels, v in series}
        with self._lock:
            for key in self._gauges:
                if key[0] == name and key[1] not in new:
                    self._gauges[key] = 0.0
            for labels, v in new.items():
                self._gauges[(name, labels)] = v

    def observe(self, name: str, value: float, **labels) -> None:
        """Record one observation into the fixed-bucket histogram
        ``name`` (buckets from ``describe_histogram``, defaulting to the
        Prometheus duration buckets)."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._observe_locked(key, value)

    def _observe_locked(self, key: tuple[str, tuple],
                        value: float) -> None:
        h = self._hists.get(key)
        if h is None:
            h = self._hists[key] = _Hist(
                self._buckets.get(key[0], DEFAULT_BUCKETS))
        buckets = h.buckets  # pinned at creation
        for i, ub in enumerate(buckets):
            if value <= ub:
                h.counts[i] += 1
                break
        else:
            h.counts[-1] += 1  # +Inf
        h.sum += value
        h.count += 1

    def bulk(self, incs=(), observations=()) -> None:
        """Apply counter increments and histogram observations under ONE
        lock acquisition. Items are ``(name, labels_tuple, value)`` with
        ``labels_tuple`` already in sorted-pairs form — the store's
        write-telemetry flush uses this so a write verb pays one hub
        lock round trip, not one per sample (the hub lock is also held
        across every /metrics render)."""
        with self._lock:
            for name, labels, v in incs:
                self._counters[(name, labels)] += v
            for name, labels, v in observations:
                self._observe_locked((name, labels), v)

    # ---- programmatic reads (the deploy observatory's snapshots) ----

    def counter_total(self, name: str) -> float:
        """Sum of counter ``name`` across every label set."""
        with self._lock:
            return sum(v for (n, _), v in self._counters.items()
                       if n == name)

    def hist_totals(self, name: str) -> tuple[float, float]:
        """(sum, count) of histogram ``name`` across every label set —
        the windowed wait-vs-work split is a delta of two of these."""
        with self._lock:
            s = c = 0.0
            for (n, _), h in self._hists.items():
                if n == name:
                    s += h.sum
                    c += h.count
            return s, c

    @staticmethod
    def _escape_label(value) -> str:
        """Prometheus text-format label value escaping: backslash,
        double quote, and newline must be escaped inside the quotes."""
        return (str(value).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    @staticmethod
    def _fmt(name: str, labels: tuple, value: float) -> str:
        if labels:
            lbl = ",".join(f'{k}="{MetricsHub._escape_label(v)}"'
                           for k, v in labels)
            return f"{name}{{{lbl}}} {value}"
        return f"{name} {value}"

    def _render_hist(self, name: str, labels: tuple, h: _Hist) -> list[str]:
        buckets = h.buckets  # pinned at creation, not the current registry
        out, cum = [], 0
        for ub, n in zip(buckets, h.counts):
            cum += n
            out.append(self._fmt(f"{name}_bucket",
                                 labels + (("le", repr(float(ub))),), cum))
        cum += h.counts[-1]
        out.append(self._fmt(f"{name}_bucket",
                             labels + (("le", "+Inf"),), cum))
        out.append(self._fmt(f"{name}_sum", labels, round(h.sum, 6)))
        out.append(self._fmt(f"{name}_count", labels, h.count))
        return out

    def render(self) -> str:
        """Prometheus text exposition format."""
        lines: list[str] = []
        with self._lock:
            by_name: dict[str, list[str]] = defaultdict(list)
            for (name, labels), v in sorted(self._counters.items()):
                by_name[name].append(self._fmt(name, labels, v))
            for (name, labels), v in sorted(self._gauges.items()):
                by_name[name].append(self._fmt(name, labels, v))
            hist_names = set()
            for (name, labels), h in sorted(self._hists.items()):
                hist_names.add(name)
                by_name[name].extend(self._render_hist(name, labels, h))
        for name, samples in sorted(by_name.items()):
            if name in self._help:
                lines.append(f"# HELP {name} {self._help[name]}")
            if name in hist_names:
                lines.append(f"# TYPE {name} histogram")
            lines.extend(samples)
        return "\n".join(lines) + "\n"


_BUCKET_RE = re.compile(
    r'^(?P<name>\w+)_bucket\{(?P<labels>.*)\} (?P<value>\S+)$')
# One label pair: quoted value, honoring \\ \" \n escapes (a comma or
# brace INSIDE the quotes must not split the pair — naive ','.split
# mis-parsed exactly the values render now escapes).
_LABEL_PAIR_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _unescape_label(value: str) -> str:
    return re.sub(r"\\(.)",
                  lambda m: {"n": "\n"}.get(m.group(1), m.group(1)),
                  value)


def parse_histograms(text: str, name: str,
                     ) -> dict[tuple, dict[float, float]]:
    """Parse a histogram's cumulative ``_bucket`` samples back out of
    the rendered exposition text: {labels-without-le: {le: cum_count}}.
    This is how the scale harness asserts its latency budget — from the
    same surface a deployed Prometheus would scrape, not from private
    runner state."""
    out: dict[tuple, dict[float, float]] = {}
    for line in text.splitlines():
        m = _BUCKET_RE.match(line)
        if not m or m.group("name") != name:
            continue
        labels, le = [], math.inf
        for k, v in _LABEL_PAIR_RE.findall(m.group("labels")):
            v = _unescape_label(v)
            if k == "le":
                le = math.inf if v == "+Inf" else float(v)
            else:
                labels.append((k, v))
        out.setdefault(tuple(sorted(labels)), {})[le] = float(
            m.group("value"))
    return out


_SAMPLE_RE = re.compile(
    r'^(?P<name>\w+?)(?:\{(?P<labels>.*)\})? (?P<value>\S+)$')


def parse_counters(text: str, name: str) -> dict[tuple, float]:
    """Parse counter/gauge samples named exactly ``name`` back out of
    rendered exposition text: {labels: value}. The benches read their
    scan/write counts through this — the same surface a deployed
    Prometheus scrapes — instead of poking store attributes."""
    out: dict[tuple, float] = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m or m.group("name") != name:
            continue
        labels = tuple(sorted(
            (k, _unescape_label(v))
            for k, v in _LABEL_PAIR_RE.findall(m.group("labels") or "")))
        out[labels] = float(m.group("value"))
    return out


def quantile_from_buckets(q: float, cum: dict[float, float]) -> float:
    """Prometheus ``histogram_quantile``: locate the bucket covering
    quantile ``q`` and interpolate linearly inside it (same estimate a
    deployed alert computes, so budget assertions here and alerts in
    production fire on the same number). Observations in the +Inf
    bucket return the largest finite upper bound, as Prometheus does."""
    total = cum.get(math.inf, 0.0)
    if total <= 0:
        return 0.0
    target = q * total
    prev_ub, prev_cum = 0.0, 0.0
    finite = [ub for ub in sorted(cum) if ub != math.inf]
    for ub in finite:
        c = cum[ub]
        if c >= target:
            if c == prev_cum:
                return ub
            return prev_ub + (ub - prev_ub) * (target - prev_cum) / (
                c - prev_cum)
        prev_ub, prev_cum = ub, c
    return finite[-1] if finite else math.inf


def subtract_buckets(after: dict[float, float], before: dict[float, float],
                     ) -> dict[float, float]:
    """Windowed view of a cumulative histogram: bucket-wise delta of two
    snapshots (what ``rate()`` does for a deployed alert)."""
    return {ub: after[ub] - before.get(ub, 0.0) for ub in after}


GLOBAL_METRICS = MetricsHub()
GLOBAL_METRICS.describe("grove_reconcile_total",
                        "Reconcile invocations per controller")
GLOBAL_METRICS.describe("grove_reconcile_errors_total",
                        "Reconcile errors per controller")
GLOBAL_METRICS.describe("grove_workqueue_depth",
                        "Current workqueue depth per controller")
GLOBAL_METRICS.describe("grove_gang_placements_total",
                        "Gangs placed by the scheduler")
GLOBAL_METRICS.describe("grove_store_objects",
                        "Objects in the store per kind")
GLOBAL_METRICS.describe_histogram(
    "grove_reconcile_duration_seconds",
    "Reconcile wall time per controller (controller-runtime "
    "controller_runtime_reconcile_time_seconds analog)")
GLOBAL_METRICS.describe_histogram(
    "grove_workqueue_wait_seconds",
    "Time a request spends queued past its ready time before a worker "
    "picks it up (workqueue_queue_duration_seconds analog)")
GLOBAL_METRICS.describe_histogram(
    "grove_sched_place_pass_seconds",
    "Wall time of one scheduler placement pass per backend (the "
    "PodGang-schedule-latency surface the BASELINE metric reads)")
GLOBAL_METRICS.describe(
    "grove_sched_snapshot_rebuilds_total",
    "Placement-snapshot full rebuilds forced by outside writers "
    "mid-pass (incremental accounting covered every other bind)")
GLOBAL_METRICS.describe(
    "grove_informer_cache_objects",
    "Objects in the shared informer cache per kind")
GLOBAL_METRICS.describe(
    "grove_informer_cache_reads_total",
    "List reads served from the informer cache per kind (the direct "
    "store path is the complement: grove_informer_relists_total plus "
    "whatever GROVE_INFORMER=0 sends around the cache)")
GLOBAL_METRICS.describe(
    "grove_informer_relists_total",
    "Full cache reseeds per kind and reason (seed=first use, "
    "gap=history ring no longer covered the cursor)")
GLOBAL_METRICS.describe_histogram(
    "grove_informer_event_lag_seconds",
    "Delay from event emission to informer cache application "
    "(pull-fed informers apply at read time, so this is also the "
    "staleness a cached read repaired)",
    # Pinned sub-millisecond-to-seconds buckets: informer lag at
    # steady state is micro-to-milliseconds; the default duration
    # buckets would flatten everything into the first bucket.
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
             0.1, 0.25, 0.5, 1.0, 2.5))
# Control-plane observatory (runtime/sweepobs.py,
# docs/design/controlplane-observatory.md): per-sweep attribution
# rolled up by trigger cause, plus the write-amplification and
# watch-lag SLO gauges grovectl controlplane-status judges.
GLOBAL_METRICS.describe_histogram(
    "grove_sweep_seconds",
    "Reconcile sweep wall time per controller and trigger cause "
    "(watch:<Kind>|resync|requeue|backoff|panic|external — the "
    "workqueue hint that woke the request)")
GLOBAL_METRICS.describe_histogram(
    "grove_sweep_writes",
    "Store write-verb CALLS issued by one reconcile sweep, per "
    "controller and verb (a batched patch_status_many is one call "
    "however many items — the store-RPC-rate analog)",
    buckets=SWEEP_WRITE_BUCKETS)
GLOBAL_METRICS.describe(
    "grove_sweep_write_amp",
    "Recent writes per changed object per controller (the write-"
    "amplification ledger's windowed estimate; zeroed on park/demote "
    "so a standby never advertises live load)")
GLOBAL_METRICS.describe(
    "grove_informer_watch_lag_seconds",
    "Staleness of the most recently applied watch event per kind (the "
    "watch-lag SLO estimator, judged against GROVE_WATCH_LAG_SLO)")
GLOBAL_METRICS.describe(
    "grove_informer_watch_lag_breached",
    "1 while a kind's watch lag exceeds the configured staleness "
    "target, else 0 (grovectl controlplane-status exits 1 on breach)")
# Gang lifecycle SLO surface, derived from trace milestones
# (runtime/trace.py): one observation per gang per milestone, measured
# from the trace's mint (the root object's create).
GLOBAL_METRICS.describe_histogram(
    "grove_gang_time_to_scheduled_seconds",
    "Create-to-Scheduled latency per gang (trace mint to the "
    "scheduler's Scheduled condition flip, from lifecycle trace "
    "milestones)",
    buckets=LIFECYCLE_BUCKETS)
GLOBAL_METRICS.describe_histogram(
    "grove_gang_time_to_ready_seconds",
    "Create-to-Ready latency per gang (trace mint to every gang pod "
    "reporting Ready — the time-to-ready SLO the scale harness "
    "asserts)",
    buckets=LIFECYCLE_BUCKETS)
# Placement explainability surface (docs/design/placement-explain.md):
# why-is-my-gang-pending as metrics, alertable without log-diving.
GLOBAL_METRICS.describe(
    "grove_gang_unschedulable",
    "Currently-unschedulable gangs per diagnosis reason "
    "(ChipShortfall|TopologyPruned|Fragmented|SelectorMismatch|"
    "PreemptionRejected|StragglerUnplaced; reasons zero when they "
    "drain)")
GLOBAL_METRICS.describe_histogram(
    "grove_gang_pending_seconds",
    "Time from a gang's first failed placement attempt to its "
    "successful schedule (observed once at schedule; the diagnosis is "
    "cleared at the same moment)",
    buckets=PENDING_BUCKETS)
GLOBAL_METRICS.describe(
    "grove_state_objects",
    "Objects per kind and status phase, fed from the shared informer "
    "caches (kube-state-metrics analog; phase empty for kinds without "
    "one)")
# Expectation-store observability (runtime/expectations.py): the
# informer-staleness barrier's leak detector — a pending count that
# never drains, or any expiry, means watch events are being lost
# (the double-create hazard's precursor, SURVEY.md §7).
GLOBAL_METRICS.describe(
    "grove_expectations_pending",
    "Outstanding unobserved create/delete expectation UIDs per "
    "controller (the informer-staleness barrier; should drain to 0 "
    "within an event round trip)")
GLOBAL_METRICS.describe(
    "grove_expectations_expired_total",
    "Expectations that expired by TTL instead of being observed per "
    "controller — each one is a lost or badly lagged watch event "
    "(also surfaced as an ExpectationExpired Warning event)")
GLOBAL_METRICS.describe_histogram(
    "grove_lifecycle_phase_seconds",
    "Per-phase gang lifecycle durations (phase=create_to_gang|"
    "gang_to_scheduled|scheduled_to_started|started_to_ready)",
    buckets=LIFECYCLE_BUCKETS)
# Write-path observability surface (docs/design/
# write-path-observability.md): every store write attributed to kind,
# verb, and writer; GROVE_WRITE_OBS=0 disables the collection.
GLOBAL_METRICS.describe(
    "grove_store_writes_total",
    "Committed store mutations per kind, verb (create|update|"
    "update_status|patch_status|delete) and writer (the reconciling "
    "controller, or 'direct' for unattributed clients); cascade "
    "deletes count one delete per removed object")
GLOBAL_METRICS.describe(
    "grove_store_conflicts_total",
    "Optimistic-concurrency rejections (stale resource_version) per "
    "kind, verb, and writer — sustained conflicts mean two writers "
    "fight over one object")
GLOBAL_METRICS.describe(
    "grove_store_noop_writes_total",
    "Status writes suppressed as byte-identical no-ops per kind and "
    "writer (the steady-state self-trigger guard; a high rate is "
    "wasted reconcile work, not wasted store writes)")
GLOBAL_METRICS.describe(
    "grove_store_events_total",
    "Event-ring appends per kind and event type — the watch fan-out "
    "cost every committed write pays")
GLOBAL_METRICS.describe(
    "grove_store_list_scans_total",
    "List-shaped store scans per kind (list + list_snapshot; the "
    "metric twin of Store.list_scans — benches and dashboards read "
    "this text, not store internals)")
GLOBAL_METRICS.describe_histogram(
    "grove_store_lock_wait_seconds",
    "Time a write verb waited to acquire the store lock (writer "
    "contention; per public verb)",
    buckets=LOCK_BUCKETS)
GLOBAL_METRICS.describe_histogram(
    "grove_store_lock_hold_seconds",
    "Time a write verb held the store lock (critical-section length — "
    "what every other store caller waited behind; per public verb)",
    buckets=LOCK_BUCKETS)
# Per-controller write-path attribution: work duration (the
# workqueue_work_duration_seconds analog) and requeue/retry counters
# complement grove_workqueue_wait_seconds.
GLOBAL_METRICS.describe_histogram(
    "grove_workqueue_work_seconds",
    "Time a worker spends on one dequeued request, pickup to done "
    "(workqueue_work_duration_seconds analog; queue-wait vs work-time "
    "is the deploy observatory's congestion split)")
GLOBAL_METRICS.describe(
    "grove_reconcile_requeues_total",
    "Requeues per controller and reason (backoff=error retry with "
    "exponential delay, requeue_after=explicit delayed requeue, "
    "panic=reconcile raised)")
# Deploy observatory (runtime/deploywatch.py): per-PCS deploy
# milestones, observed once per deploy when the PCS reaches Available.
GLOBAL_METRICS.describe_histogram(
    "grove_deploy_duration_seconds",
    "PodCliqueSet create-to-milestone durations per phase "
    "(first_pod|pods_created|scheduled|started|ready|available), "
    "observed once per deploy at Available — the 1000-pod "
    "deploy-budget surface (SURVEY.md §6)",
    buckets=LIFECYCLE_BUCKETS)
# Serving observatory (runtime/servingwatch.py, docs/design/
# serving-slo.md): engine-pushed SLO signals aggregated per scaling
# scope, plus the autoscaler decisions acting on them.
GLOBAL_METRICS.describe(
    "grove_serving_signal",
    "Aggregated engine serving signal per scaling scope and metric "
    "(queue depth summed, KV utilization averaged, TTFT/TPOT "
    "percentiles maxed across reporters per the registry's "
    "aggregation modes; scopes zero when their samples expire)")
GLOBAL_METRICS.describe(
    "grove_serving_reporters",
    "Live engine reporters per scaling scope (fresh samples inside "
    "the registry TTL; fewer reporters than replicas is a liveness "
    "finding, not a latency one)")
GLOBAL_METRICS.describe(
    "grove_serving_slo_breached",
    "1 while a scope's autoscaling target metric exceeds its target "
    "value (the alertable twin of the autoscaler's scale-out trigger)")
GLOBAL_METRICS.describe(
    "grove_autoscaler_desired_replicas",
    "Autoscaler-desired replicas per scalable object (post-"
    "stabilization; spec.replicas while the signal is absent; zeroed "
    "when the object drains)")
GLOBAL_METRICS.describe(
    "grove_autoscaler_decisions_total",
    "Applied scaling decisions per object and direction (up|down) — "
    "each has a matching ScaledUp/ScaledDown event with signal vs "
    "target")
# Defragmentation engine (grove_tpu/defrag, docs/design/defrag.md):
# active placement repair acting on the explain diagnoses.
GLOBAL_METRICS.describe(
    "grove_defrag_plans_proposed_total",
    "Migration plans adopted for execution by the defrag controller "
    "(each provably unwedges a pending gang at proposal time)")
GLOBAL_METRICS.describe(
    "grove_defrag_plans_executed_total",
    "Migrations completed: the victim gang relanded whole on its "
    "reserved target slice and the hold was released")
GLOBAL_METRICS.describe(
    "grove_defrag_plans_aborted_total",
    "Migrations aborted per reason (hold-timeout|hold-lost|superseded|"
    "rebind-timeout|target-lost|victim-gone|disabled) — every abort "
    "releases its reservation and annotation")
GLOBAL_METRICS.describe(
    "grove_defrag_chips_freed_total",
    "Chips vacated from fragmented domains by completed migrations "
    "(the defragmented-capacity odometer)")
GLOBAL_METRICS.describe(
    "grove_defrag_inflight",
    "1 while a migration is executing (hold/drain/rebind), else 0 — "
    "the executor runs one plan at a time")
GLOBAL_METRICS.describe_histogram(
    "grove_defrag_migration_seconds",
    "Wall time of one completed migration, hold creation to full "
    "reland on the target slice",
    buckets=LIFECYCLE_BUCKETS)
# Disruption contract + spot-slice reclamation (grove_tpu/disruption,
# docs/design/disruption-contract.md): every planned eviction's
# checkpoint barrier, and the reclaim controller's evacuations.
GLOBAL_METRICS.describe(
    "grove_disruption_notices_total",
    "DisruptionNotices posted per reason (defrag-migration|"
    "rolling-update|spot-reclaim) — coalesced joins onto a live notice "
    "do not count again")
GLOBAL_METRICS.describe(
    "grove_disruption_acks_total",
    "Checkpoint-barrier acknowledgments per source (workload=a "
    "registered responder's checkpoint completed, auto=no responder "
    "registered so nothing needed flushing)")
GLOBAL_METRICS.describe(
    "grove_disruption_expired_total",
    "Barriers that hit their deadline unacked per reason — the "
    "eviction proceeded anyway, stamped barrier=expired (the workload "
    "delays, never vetoes)")
GLOBAL_METRICS.describe(
    "grove_disruption_evictions_total",
    "Planned evictions executed per reason and barrier verdict "
    "(acked|expired) — the disruption-contract invariant's counters")
GLOBAL_METRICS.describe(
    "grove_disruption_ack_failures_total",
    "Checkpoint responder failures per reason (each retries with "
    "exponential backoff until the ack lands or the deadline expires)")
GLOBAL_METRICS.describe(
    "grove_disruption_evacuations_total",
    "Spot-reclaim evacuations started (one per gang on reclaim-"
    "noticed capacity)")
GLOBAL_METRICS.describe(
    "grove_disruption_evacuations_completed_total",
    "Evacuations that relanded their gang Ready on surviving capacity")
GLOBAL_METRICS.describe(
    "grove_disruption_evacuations_aborted_total",
    "Evacuations abandoned per reason (victim-gone|rebind-timeout) — "
    "every abort releases its hold and notice; self-heal owns the "
    "gang afterward")
GLOBAL_METRICS.describe(
    "grove_disruption_reholds_total",
    "Mid-evacuation hold re-takes after a reservation TTL expiry or "
    "loss — the evacuation requeues instead of stranding a "
    "half-drained gang")
GLOBAL_METRICS.describe(
    "grove_disruption_inflight",
    "Gang evacuations currently executing (notice/barrier/hold/"
    "reland)")
GLOBAL_METRICS.describe_histogram(
    "grove_disruption_barrier_wait_seconds",
    "Notice post to checkpoint ack (auto-acks observe ~0) — how long "
    "planned evictions wait on workloads",
    buckets=LIFECYCLE_BUCKETS)
GLOBAL_METRICS.describe_histogram(
    "grove_disruption_reclaim_to_ready_seconds",
    "Spot-reclamation notice to the evacuated gang Ready again on "
    "surviving capacity — the reclaim robustness headline "
    "(make bench-reclaim pins it)",
    buckets=LIFECYCLE_BUCKETS)
GLOBAL_METRICS.describe(
    "grove_autoscaler_conflicts_total",
    "Scale writes rejected by the store (conflict or validation) per "
    "object — a sustained rate means something else fights the "
    "autoscaler over replicas")
# HA control plane (grove_tpu/ha, docs/design/ha.md): leadership role,
# fencing epoch, transition counts, and the failover-resume SLO.
GLOBAL_METRICS.describe(
    "grove_leader",
    "1 on the replica currently holding leadership, 0 on standbys "
    "and demoted replicas (labeled by replica name)")
GLOBAL_METRICS.describe(
    "grove_leadership_epoch",
    "The store's current fencing epoch (monotonic term number; bumps "
    "exactly once per leadership transition)")
GLOBAL_METRICS.describe(
    "grove_leadership_transitions_total",
    "Leadership transitions observed by this process per direction "
    "(promoted|demoted)")
GLOBAL_METRICS.describe(
    "grove_store_fenced_writes_total",
    "Writes rejected by the leadership fence (writer epoch older than "
    "the store's) per kind, verb, and writer — a deposed leader's "
    "zombie writes made visible")
# Data-plane observatory (serving/xprof.py, docs/design/
# data-plane-observability.md): XLA compile/step/memory telemetry for
# the serving engine — all host-side, GROVE_XPROF=0 disables.
GLOBAL_METRICS.describe_histogram(
    "grove_compile_seconds",
    "XLA compile wall time per engine-compiled function (prefill|"
    "step|step_sampled|step_block|step_block_sampled), recorded by "
    "the CompileTracker when a dispatch grew the jit cache",
    buckets=COMPILE_BUCKETS)
GLOBAL_METRICS.describe(
    "grove_recompiles_total",
    "Executable builds per compiled fn and reason (first=expected "
    "warm-up lowering, shape-change=new argument signature, "
    "cache-evict=signature seen before but rebuilt) — any non-first "
    "rate on a serving engine means shapes are churning")
GLOBAL_METRICS.describe(
    "grove_recompile_storms_total",
    "Recompile-storm warnings: more than the threshold of non-first "
    "compiles inside the sliding window (the dynamic-shape-leak "
    "alarm; each one also logs a warning)")
GLOBAL_METRICS.describe_histogram(
    "grove_device_step_seconds",
    "Sampled per-step device time by phase (prefill|step|sample|"
    "host_transfer), measured host-side with synced dispatch ends by "
    "the decode-step flight recorder — every Nth dispatch, never on "
    "the JIT path",
    buckets=DEVICE_STEP_BUCKETS)
GLOBAL_METRICS.describe(
    "grove_hbm_bytes",
    "Engine memory accounting per kind (kv_cache|weights|workspace|"
    "total) and scope, from device.memory_stats() where the backend "
    "supports it and model-derived byte counts otherwise (the "
    "payload's source field says which)")
GLOBAL_METRICS.describe_histogram(
    "grove_failover_resume_seconds",
    "Leader death to reconcile observably resumed on the promoted "
    "replica (promotion wall time: fence + state load + controller "
    "warm start), observed once per promotion",
    buckets=LIFECYCLE_BUCKETS)
# Disaggregated prefill→decode serving (serving/handoff.py,
# docs/design/disaggregated-serving.md): the KV block handoff seam,
# counted on the ADOPTING (decode) side — one bump per adopted
# request. GROVE_DISAGG=0 leaves these at zero.
GLOBAL_METRICS.describe(
    "grove_handoff_blocks_total",
    "KV blocks physically transferred prefill→decode (cold blocks "
    "only — decode-side prefix-cache hits ride shared refs and never "
    "move; a high shared:cold ratio is the cache doing the handoff's "
    "work)")
GLOBAL_METRICS.describe(
    "grove_handoff_bytes_total",
    "Bytes the transferred blocks represent (K + V + int8 scales "
    "when quantized — the live pool's per-block nbytes, the figure "
    "the decode bench cross-checks)")
GLOBAL_METRICS.describe_histogram(
    "grove_handoff_seconds",
    "Per-request handoff adoption wall time (every cold block's pool "
    "copy, synced end-to-end), observed on xprof-sampled adoptions "
    "only — the transfer seam's latency distribution",
    buckets=DEVICE_STEP_BUCKETS)
# Request observatory (serving/reqtrace.py,
# docs/design/request-tracing.md): per-request phase attribution.
# Spans sub-millisecond queue waits through multi-second preemption
# storms, so the ladder is wider than the duration defaults on both
# ends. One observation per phase per FINISHED request (unconditional
# seam stamps, never the sampled per-tick decoration).
REQUEST_PHASE_BUCKETS = (1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
                         0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                         60.0)
GLOBAL_METRICS.describe_histogram(
    "grove_request_phase_seconds",
    "Wall seconds one finished request spent in each serving phase "
    "(queue_wait|prefix_match|prefill|handoff|decode|"
    "preempt_recompute), accumulated from unconditional lifecycle "
    "stamps and observed once per phase at completion — the p99 "
    "attribution family (argmax = the request's dominant phase)",
    buckets=REQUEST_PHASE_BUCKETS)
GLOBAL_METRICS.describe(
    "grove_reqtrace_dropped_total",
    "Request traces shed by the observatory's bounds (live-cap "
    "overflow on a submit storm, finished-ring eviction churn) — "
    "nonzero means /debug/requests is a sample of the traffic, not "
    "the census; GROVE_REQTRACE_RING/GROVE_REQTRACE_LIVE raise the "
    "bounds")
