"""Prometheus-style metrics for the control plane.

Role parity with the reference's controller-runtime metrics server
(config types.go:202-212): counters/gauges with labels, rendered in the
Prometheus text exposition format by ``render``. The manager exposes
``Manager.metrics_text()``; a real deployment serves it over HTTP.
"""

from __future__ import annotations

import threading
from collections import defaultdict


class MetricsHub:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, tuple], float] = defaultdict(float)
        self._gauges: dict[tuple[str, tuple], float] = {}
        self._help: dict[str, str] = {}

    def describe(self, name: str, help_text: str) -> None:
        self._help[name] = help_text

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] += value

    def set(self, name: str, value: float, **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = value

    @staticmethod
    def _fmt(name: str, labels: tuple, value: float) -> str:
        if labels:
            lbl = ",".join(f'{k}="{v}"' for k, v in labels)
            return f"{name}{{{lbl}}} {value}"
        return f"{name} {value}"

    def render(self) -> str:
        """Prometheus text exposition format."""
        lines: list[str] = []
        with self._lock:
            by_name: dict[str, list[str]] = defaultdict(list)
            for (name, labels), v in sorted(self._counters.items()):
                by_name[name].append(self._fmt(name, labels, v))
            for (name, labels), v in sorted(self._gauges.items()):
                by_name[name].append(self._fmt(name, labels, v))
        for name, samples in sorted(by_name.items()):
            if name in self._help:
                lines.append(f"# HELP {name} {self._help[name]}")
            lines.extend(samples)
        return "\n".join(lines) + "\n"


GLOBAL_METRICS = MetricsHub()
GLOBAL_METRICS.describe("grove_reconcile_total",
                        "Reconcile invocations per controller")
GLOBAL_METRICS.describe("grove_reconcile_errors_total",
                        "Reconcile errors per controller")
GLOBAL_METRICS.describe("grove_workqueue_depth",
                        "Current workqueue depth per controller")
GLOBAL_METRICS.describe("grove_gang_placements_total",
                        "Gangs placed by the scheduler")
GLOBAL_METRICS.describe("grove_store_objects",
                        "Objects in the store per kind")
