"""Informer layer: shared watch-driven caches + indexed listers.

Role parity with client-go's SharedInformerFactory as the reference
operator uses it (SURVEY.md §1 L2): the apiserver is LISTed once per
kind, a reflector applies the watch stream to an in-memory cache, and
every controller read is an indexed cache lookup — reconcile never
re-LISTs the store on the hot path.

This framework's twist is that the store is (usually) in-process, so
the reflector can be *pull-on-read*: every cached read first drains the
store's event ring from the informer's cursor (``Store.replay`` — the
same resumable machinery the wire watch uses), which makes the cache
exactly as fresh as the store at read time. Read-your-own-write is
therefore structural: a reconcile that just wrote pulls its own event
before the next read, no barrier dance required. Over the wire there is
no synchronous pull; a ``Reflector`` thread pushes events from
``HttpClient.watch_events`` (with the shared relist-and-resume helper)
and readers that need the barrier call ``Informer.wait_for_rv``.

Cache objects are SHARED, like ``Store.list_snapshot`` output (they are
the same per-version clones, plus the event-ring clones): callers must
not mutate them — ``clone()`` before editing, exactly the scheduler
snapshot's contract. A history-ring gap (local overflow or wire
``WatchGoneError``) re-seeds the cache with a full relist instead of
failing the consumer.

``GROVE_INFORMER=0`` restores direct store reads in ``CachedClient``
(the escape hatch; see docs/design/informer-cache.md).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.metrics import GLOBAL_METRICS
from grove_tpu.store.client import Client
from grove_tpu.store.store import (
    EventType,
    Store,
    matches_fields,
    matches_labels,
)

INFORMER_ENV = "GROVE_INFORMER"

# Kinds that never enter the shared cache. Secrets carry credentials:
# the store's authorization chain decides per-actor visibility on every
# read, and a shared cache would be a side channel around it.
UNCACHED_KINDS = frozenset({"Secret"})


def informer_enabled() -> bool:
    """Read the escape hatch per call: flipping GROVE_INFORMER=0 at any
    point (tests, incident mitigation) restores direct-list reads
    without rebuilding clients."""
    return os.environ.get(INFORMER_ENV, "1") != "0"


class LocalStoreSource:
    """Pull transport over the in-process store: the event history ring
    IS the watch stream (same seqs, same 410-gone semantics as the wire
    long-poll), and a relist is one shared-clone ``list_snapshot``."""

    can_pull = True

    def __init__(self, store: Store):
        self._store = store

    def relist(self, kind_cls: type) -> tuple[int, list[Any]]:
        return self._store.list_snapshot(kind_cls, namespace=None)

    def pull(self, kind: str, since: int):
        return self._store.replay(since, kinds={kind})

    def tip(self) -> int:
        """Highest seq currently in the event ring, read WITHOUT the
        store lock (deque append is atomic; a racing write is caught by
        the caller's next sync — and never by a reader that issued the
        write itself, since emit precedes the write's return). Lets the
        every-read sync skip the locked replay when nothing happened."""
        h = self._store._history
        return h[-1][0] if h else 0


class WireSource:
    """Relist transport over HTTP for push-fed informers. The rv is
    fetched BEFORE the list: any write landing between the two is
    replayed by the resuming watch and deduped by the per-object rv
    guard in ``Informer._apply_locked`` (listing first would instead
    lose writes that land between list and rv fetch)."""

    can_pull = False

    def __init__(self, http: Any):
        self._http = http

    def relist(self, kind_cls: type) -> tuple[int, list[Any]]:
        rv = self._http.current_rv()
        return rv, self._http.list(kind_cls, namespace=None)


class Lister:
    """Indexed read views over one informer's cache.

    Every method syncs the informer first (free for push-fed informers)
    and returns SHARED objects — the ``list_snapshot`` contract: do not
    mutate; ``clone()`` before editing.
    """

    def __init__(self, informer: "Informer"):
        self._inf = informer

    def get(self, name: str, namespace: str = "default") -> Any | None:
        self._inf.sync()
        with self._inf._lock:
            return self._inf._objects.get((namespace, name))

    def list(self, namespace: str | None = None,
             selector: dict[str, str] | None = None,
             fields: dict[str, str] | None = None) -> list[Any]:
        """Store-list semantics (namespace/label/field filters, sorted
        by name) served from the cache; a label selector resolves
        through the label index instead of scanning every object."""
        self._inf.sync()
        with self._inf._lock:
            if selector:
                refs = self._inf._label_candidates(selector)
                # A single-pair selector IS the index key: the posting
                # list already guarantees the match (the hottest list
                # shape — pods of one clique — skips re-verification).
                verify = len(selector) > 1
            else:
                refs = self._inf._objects.values()
                verify = False
            out = [o for o in refs
                   if (namespace is None or o.meta.namespace == namespace)
                   and (not verify or matches_labels(o, selector))
                   and (fields is None or matches_fields(o, fields))]
        out.sort(key=lambda o: o.meta.name)
        return out

    def by_label(self, selector: dict[str, str],
                 namespace: str | None = None) -> list[Any]:
        return self.list(namespace, selector)

    def by_owner(self, namespace: str, owner_ref: Any) -> list[Any]:
        """Objects whose ``meta.owner_references`` include the given
        owner (an OwnerReference, or a ``(kind, name)`` pair) in
        ``namespace`` — the controller-owned-children lookup, without
        the linear scan."""
        kind = getattr(owner_ref, "kind", None)
        name = getattr(owner_ref, "name", None)
        if kind is None:
            kind, name = owner_ref
        self._inf.sync()
        with self._inf._lock:
            keys = self._inf._by_owner.get((namespace, kind, name), ())
            out = [self._inf._objects[k] for k in keys
                   if k in self._inf._objects]
        out.sort(key=lambda o: o.meta.name)
        return out


class Informer:
    """One kind's watch cache: seeded by a relist at a resource version,
    kept current by the event stream, indexed by label pair and owner
    reference. Shared by every controller in a manager (one per kind)."""

    def __init__(self, kind_cls: type, source: Any):
        self.kind_cls = kind_cls
        self.KIND: str = kind_cls.KIND
        self._source = source
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._objects: dict[tuple[str, str], Any] = {}
        # (label_key, label_value) -> object keys; (ns, kind, name) of
        # an owner reference -> object keys. Maintained incrementally
        # per event — a lookup never rescans the cache.
        self._by_label: dict[tuple[str, str], set[tuple[str, str]]] = {}
        self._by_owner: dict[tuple[str, str, str], set[tuple[str, str]]] = {}
        self.rv = 0            # last seq observed (seed rv or event seq)
        self.relists = 0
        self.events_applied = 0
        # Watch-lag SLO feed (runtime/sweepobs.py): lag of the most
        # recently applied timestamped event (the cache's current
        # staleness estimator), lifetime max, and a count. Updated
        # under the informer lock alongside the lag list; replayed
        # events a relist already superseded never reach here (the rv
        # guard in _apply_locked returns before the lag append).
        self.lag_events = 0
        self.lag_last_s = 0.0
        self.lag_max_s = 0.0
        self._seeded = False
        self._lister = Lister(self)   # one shared view; Lister is stateless
        self.log = get_logger(f"informer.{self.KIND}")

    # ---- freshness ----

    def sync(self) -> None:
        """Drain pending events from a pull source (no-op for push-fed
        informers — their Reflector thread is the writer). Seeds on
        first use; a cursor that fell off the history ring relists."""
        if self._seeded and (not self._source.can_pull
                             or self._source.tip() <= self.rv):
            return
        lags: list[float] = []
        count = None
        with self._lock:
            if not self._seeded:
                self._relist_locked("seed")
                count = len(self._objects)
            if self._source.can_pull:
                events, ok, scanned = self._source.pull(self.KIND, self.rv)
                if not ok:
                    self._relist_locked("gap")
                    count = len(self._objects)
                else:
                    for _seq, ev in events:
                        self._apply_locked(ev.type, ev.obj, ev.ts, lags)
                    if scanned > self.rv:
                        self.rv = scanned
                    if events:
                        count = len(self._objects)
        self._export(lags, count)

    def apply_event(self, seq: int, etype: Any, obj: Any,
                    ts: float = 0.0) -> None:
        """Push one watch event into the cache (the wire Reflector's
        entry point). Stale seqs after a reseed are absorbed by the
        per-object rv guard; the cursor never moves backwards."""
        if isinstance(etype, str):
            etype = EventType(etype)
        lags: list[float] = []
        with self._cond:
            self._apply_locked(etype, obj, ts, lags)
            if seq > self.rv:
                self.rv = seq
            count = len(self._objects)
            self._cond.notify_all()
        self._export(lags, count)

    def relist_now(self, reason: str = "gap") -> int:
        """Force a full reseed (the wire gap path: missed events are
        unrecoverable, so derived state must be rebuilt from a list).
        Returns the reseed's rv — the Reflector resumes its watch there
        so the reseed-to-resume window is replayed, not skipped."""
        with self._cond:
            self._relist_locked(reason)
            count = len(self._objects)
            rv = self.rv
            self._cond.notify_all()
        self._export([], count)
        return rv

    def wait_for_rv(self, rv: int, timeout: float = 5.0) -> bool:
        """Read-your-own-write barrier: block until the cache observed
        events through ``rv``. Pull-fed informers satisfy it
        synchronously (sync() drains to the store's current rv)."""
        if self._source.can_pull:
            self.sync()
            return self.rv >= rv
        with self._cond:
            return self._cond.wait_for(lambda: self.rv >= rv, timeout)

    # ---- cache mutation (callers hold the lock) ----

    def _relist_locked(self, reason: str) -> None:
        rv, objs = self._source.relist(self.kind_cls)
        self._objects = {(o.meta.namespace, o.meta.name): o for o in objs}
        self._by_label = {}
        self._by_owner = {}
        for key, obj in self._objects.items():
            self._index_locked(key, obj)
        if rv > self.rv:
            self.rv = rv
        self._seeded = True
        self.relists += 1
        GLOBAL_METRICS.inc("grove_informer_relists_total",
                           kind=self.KIND, reason=reason)

    def _apply_locked(self, etype: EventType, obj: Any, ts: float,
                      lags: list[float]) -> None:
        key = (obj.meta.namespace, obj.meta.name)
        old = self._objects.get(key)
        if etype is EventType.DELETED:
            if old is not None:
                self._unindex_locked(key, old)
                del self._objects[key]
        else:
            # rv guard: a relist may have seeded a newer version than a
            # still-in-flight (or replay-overlapped) event carries.
            if old is not None and \
                    old.meta.resource_version >= obj.meta.resource_version:
                return
            if old is not None:
                self._unindex_locked(key, old)
            self._objects[key] = obj
            self._index_locked(key, obj)
        self.events_applied += 1
        if ts > 0.0:
            lag = max(0.0, time.time() - ts)
            lags.append(lag)
            self.lag_events += 1
            self.lag_last_s = lag
            if lag > self.lag_max_s:
                self.lag_max_s = lag

    def _index_locked(self, key: tuple[str, str], obj: Any) -> None:
        for pair in obj.meta.labels.items():
            self._by_label.setdefault(pair, set()).add(key)
        for ref in obj.meta.owner_references:
            self._by_owner.setdefault(
                (obj.meta.namespace, ref.kind, ref.name), set()).add(key)

    def _unindex_locked(self, key: tuple[str, str], obj: Any) -> None:
        for pair in obj.meta.labels.items():
            keys = self._by_label.get(pair)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._by_label[pair]
        for ref in obj.meta.owner_references:
            okey = (obj.meta.namespace, ref.kind, ref.name)
            keys = self._by_owner.get(okey)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._by_owner[okey]

    def _label_candidates(self, selector: dict[str, str]) -> list[Any]:
        """Smallest posting list among the selector's pairs (full match
        is re-verified by the caller — intersection for free)."""
        best: set[tuple[str, str]] | None = None
        for pair in selector.items():
            keys = self._by_label.get(pair)
            if keys is None:
                return []
            if best is None or len(keys) < len(best):
                best = keys
        return [self._objects[k] for k in (best or ())]

    # ---- observability ----

    def _export(self, lags: list[float], count: int | None) -> None:
        # Outside the informer lock: the metrics hub's global lock is
        # held across every /metrics render (see _DelayQueue.get).
        for lag in lags:
            GLOBAL_METRICS.observe("grove_informer_event_lag_seconds",
                                   lag, kind=self.KIND)
        if count is not None:
            GLOBAL_METRICS.set("grove_informer_cache_objects", count,
                               kind=self.KIND)

    def lag_snapshot(self) -> dict:
        """Watch-lag stats for the control-plane observatory's SLO
        judge (one lock round trip; zeros before any timestamped
        event has applied)."""
        with self._lock:
            return {"events": self.lag_events,
                    "last_s": self.lag_last_s,
                    "max_s": self.lag_max_s}

    def lister(self) -> Lister:
        return self._lister

    def __len__(self) -> int:
        with self._lock:
            return len(self._objects)


class InformerSet:
    """Per-kind informers over one source, created lazily and shared by
    every consumer in the manager (the SharedInformerFactory role)."""

    def __init__(self, store: Store | None = None, source: Any = None):
        assert (store is None) != (source is None), \
            "pass exactly one of store/source"
        self._source = source if source is not None \
            else LocalStoreSource(store)
        self._lock = threading.Lock()
        self._informers: dict[str, Informer] = {}

    def ensure(self, kind_cls: type) -> Informer:
        with self._lock:
            inf = self._informers.get(kind_cls.KIND)
            if inf is None:
                inf = self._informers[kind_cls.KIND] = \
                    Informer(kind_cls, self._source)
            return inf

    def for_read(self, kind_cls: type) -> Informer | None:
        """The informer serving cached reads for ``kind_cls`` — None for
        kinds that must stay on the direct (per-read authorized) path."""
        if kind_cls.KIND in UNCACHED_KINDS:
            return None
        return self.ensure(kind_cls)

    def get(self, kind: str) -> Informer | None:
        with self._lock:
            return self._informers.get(kind)

    def lister(self, kind_cls: type) -> Lister | None:
        inf = self.for_read(kind_cls)
        return inf.lister() if inf is not None else None

    def informers(self) -> list[Informer]:
        with self._lock:
            return list(self._informers.values())


class CachedClient(Client):
    """A ``Client`` whose list-shaped reads come from the shared
    informer caches: one indexed lookup over shared objects instead of
    a per-call store scan with per-object deserialization.

    Contract changes vs ``Client``:
    - ``list`` returns SHARED objects (the ``list_snapshot`` contract):
      callers must ``clone()`` before mutating. Reconcilers that edit
      a listed object clone first (see controllers/*).
    - ``get`` and every write stay on the direct store path — a point
      get is already O(1) through the store's per-version bytes cache,
      and writes must see first-writer-wins conflicts immediately.

    Staleness guard: every write records its resource version in a
    client-wide barrier; a later cached read first waits for the
    informer to observe events through that rv
    (``Informer.wait_for_rv``). The barrier is shared, not per-thread:
    reconcilers fan writes out through the shared task pool
    (run_with_slow_start), so the thread that wrote is routinely not
    the thread that re-reads. Pull-fed informers satisfy the barrier
    synchronously — the read's own sync drains the ring past the write
    — so the wait only ever blocks on push-fed (wire) caches; a barrier
    that times out there is logged loudly rather than silently serving
    a stale read.

    With ``GROVE_INFORMER=0`` every read falls back to the direct
    store path (bit-identical behavior, measured by the reconcile
    equivalence test).
    """

    def __init__(self, inner: Client, informers: InformerSet):
        super().__init__(inner._store, inner.actor)
        self.informers = informers
        self._barrier_lock = threading.Lock()
        self._barrier_rv = 0
        self.log = get_logger("cachedclient")

    # ---- rv barrier ----

    def _record_write(self, obj: Any) -> Any:
        with self._barrier_lock:
            if obj.meta.resource_version > self._barrier_rv:
                self._barrier_rv = obj.meta.resource_version
        return obj

    def create(self, obj: Any) -> Any:
        return self._record_write(super().create(obj))

    def update(self, obj: Any) -> Any:
        return self._record_write(super().update(obj))

    def update_status(self, obj: Any) -> Any:
        return self._record_write(super().update_status(obj))

    def patch_status(self, kind_cls: type, name: str, patch: dict,
                     namespace: str = "default") -> Any:
        return self._record_write(
            super().patch_status(kind_cls, name, patch, namespace))

    def delete(self, kind_cls: type, name: str,
               namespace: str = "default") -> None:
        super().delete(kind_cls, name, namespace)
        # delete returns nothing; the store's current rv bounds the
        # cascade's seqs, so it is a safe (if generous) barrier.
        rv = self._store.current_rv()
        with self._barrier_lock:
            if rv > self._barrier_rv:
                self._barrier_rv = rv

    # ---- reads ----

    def list(self, kind_cls: type, namespace: str | None = "default",
             selector: dict[str, str] | None = None,
             fields: dict[str, str] | None = None) -> list[Any]:
        inf = self.informers.for_read(kind_cls) if informer_enabled() \
            else None
        if inf is None:
            return super().list(kind_cls, namespace, selector, fields)
        GLOBAL_METRICS.inc("grove_informer_cache_reads_total",
                           kind=kind_cls.KIND)
        if not inf._source.can_pull:
            # Push-fed cache: block until it observed our writes. A
            # pull-fed cache satisfies the barrier inside the read's
            # own sync (it drains the ring past every prior write).
            if not inf.wait_for_rv(self._barrier_rv):
                # Proceeding on a stale cache is sometimes the right
                # availability call (kube informers are eventually
                # consistent too) but never a silent one.
                self.log.warning(
                    "informer %s missed rv barrier %d (cache at %d); "
                    "serving a possibly-stale list", kind_cls.KIND,
                    self._barrier_rv, inf.rv)
        return inf.lister().list(namespace, selector, fields)

    def lister(self, kind_cls: type) -> Lister | None:
        """Direct index access (``by_owner``/``by_label``) for consumers
        that want more than list semantics; None when the informer path
        is disabled so callers can fall back explicitly."""
        if not informer_enabled():
            return None
        return self.informers.lister(kind_cls)

    def impersonate(self, actor: str) -> "CachedClient":
        out = CachedClient(Client(self._store, actor), self.informers)
        return out


class Reflector:
    """Push driver for one wire-fed informer: seeds it with a relist,
    then applies ``HttpClient.watch_events`` through the shared
    relist-and-resume helper — a history-ring gap (410 Gone) re-seeds
    the cache instead of killing the thread."""

    def __init__(self, informer: Informer, http: Any,
                 poll_timeout: float = 10.0):
        self.informer = informer
        self.http = http
        self.poll_timeout = poll_timeout
        self.log = get_logger(f"reflector.{informer.KIND}")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._seed_rv: int | None = None  # set by start()'s relist

    def start(self) -> None:
        # Anchor the first watch at the seed's rv: writes landing
        # between the seed list and the watch connecting are replayed,
        # not silently skipped (the same contract the gap path honors).
        self._seed_rv = self.informer.relist_now("seed")
        self._thread = threading.Thread(  # grovelint: disable=thread-join-in-stop -- blocks in a wire long-poll up to poll_timeout; joining would stall every shutdown that long, and the daemon thread only READS (applies events to its own cache)
            target=self._run, name=f"reflector-{self.informer.KIND}",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # The thread blocks in a long poll; it is a daemon and the
        # server unblocks it at poll timeout.

    def _run(self) -> None:
        from grove_tpu.store.httpclient import resumable_watch_events
        for seq, etype, obj, ts in resumable_watch_events(
                self.http, kinds=[self.informer.KIND], namespace=None,
                poll_timeout=self.poll_timeout, stop=self._stop,
                on_gap=lambda: self.informer.relist_now("gap"),
                on_error=lambda e: self.log.warning(
                    "watch feed error: %s; retrying", e),
                with_ts=True, since=self._seed_rv):
            self.informer.apply_event(seq, etype, obj, ts)


def wire_informer(http: Any, kind_cls: type,
                  poll_timeout: float = 10.0) -> tuple[Informer, Reflector]:
    """Convenience: a wire-fed informer + its reflector (not started)."""
    inf = Informer(kind_cls, WireSource(http))
    return inf, Reflector(inf, http, poll_timeout)
