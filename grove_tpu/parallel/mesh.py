"""Device-mesh construction for TPU slices and multislice deployments.

Grove's orchestration layer places a PodCliqueScalingGroup replica onto one
ICI-connected TPU slice and spreads PodCliqueSet replicas over DCN (see
SURVEY.md §2.7/§2.8 and the reference's topology packing at
operator/api/core/v1alpha1/podcliqueset.go:296-309). Inside the pods, the
JAX side of that contract is a `jax.sharding.Mesh` whose axes mirror the
physical fabric:

- ``dp`` — data parallelism. Across slices (DCN) in multislice, or across
  hosts within a slice.
- ``sp`` — sequence/context parallelism (ring attention / all-to-all over
  ICI neighbors).
- ``tp`` — tensor parallelism over the fastest ICI dimension.

Axis order is outermost-to-innermost = slowest-to-fastest interconnect, so
collectives over ``tp`` ride the torus's nearest-neighbor links.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_PP = "pp"
AXIS_EP = "ep"
AXIS_SP = "sp"
AXIS_TP = "tp"

# Canonical axis order: outermost (slowest fabric) ... innermost (fastest).
# pp sits between dp and sp: stage hops are point-to-point activations —
# cheaper than sp/tp collectives, tolerant of slower links than either.
# ep (expert parallelism) sits between pp and sp: its all_to_all dispatch
# tolerates slower links than sp/tp collectives (and may cross slices for
# very large expert counts), but is chattier than pp's stage hops.
MESH_AXES = (AXIS_DP, AXIS_PP, AXIS_EP, AXIS_SP, AXIS_TP)


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A concrete (dp, pp, ep, sp, tp) factorisation of a device count."""

    dp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.pp * self.ep * self.sp * self.tp

    def axis_sizes(self) -> dict[str, int]:
        return {AXIS_DP: self.dp, AXIS_PP: self.pp, AXIS_EP: self.ep,
                AXIS_SP: self.sp, AXIS_TP: self.tp}


def _largest_pow2_divisor(n: int, cap: int) -> int:
    d = 1
    while d * 2 <= cap and n % (d * 2) == 0:
        d *= 2
    return d


def mesh_axes_for(n_devices: int, *, want_sp: bool = True,
                  max_tp: int = 8) -> MeshPlan:
    """Pick a sensible (dp, sp, tp) factorisation for ``n_devices``.

    Heuristic: give ``tp`` the largest power-of-two divisor up to ``max_tp``
    (tensor parallelism wants the fastest links and benefits most from being
    wide), then one factor of 2 to ``sp`` when available (ring attention needs
    ≥2 to exercise the ring), and the remainder to ``dp`` — then rebalance
    one factor of 2 from ``tp`` back to ``dp`` when that is the only way to
    get dp ≥ 2: a flagship plan whose every axis is > 1 exercises dp grad
    sync, ring-SP, and tp psums in ONE train step (at 8 devices this yields
    (dp=2, sp=2, tp=2), not (1, 2, 4)), and dp is the axis that scales
    across slices over DCN, so a plan without it under-represents the
    deployment shape.
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    tp = _largest_pow2_divisor(n_devices, min(max_tp, n_devices))
    rest = n_devices // tp
    sp = 1
    if want_sp and rest % 2 == 0 and rest >= 2:
        sp = 2
    dp = rest // sp
    while dp == 1 and tp > 2:
        tp //= 2
        dp *= 2
    while sp == 1 and want_sp and tp > 2:
        tp //= 2
        sp *= 2
    plan = MeshPlan(dp=dp, sp=sp, tp=tp)
    assert plan.size == n_devices, (plan, n_devices)
    return plan


def build_mesh(plan: MeshPlan | None = None,
               devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build a `Mesh` with axes (dp, sp, tp) over ``devices``.

    When ``plan`` is None, a plan is derived from the device count. Devices
    default to all visible devices. The device array is laid out so that
    adjacent devices (fastest ICI neighbours under the default enumeration)
    land on the innermost (tp) axis.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if plan is None:
        plan = mesh_axes_for(len(devices))
    if plan.size != len(devices):
        raise ValueError(
            f"mesh plan {plan} needs {plan.size} devices, have {len(devices)}")
    arr = np.array(devices).reshape(plan.dp, plan.pp, plan.ep, plan.sp,
                                    plan.tp)
    return Mesh(arr, MESH_AXES)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    """A trivial 1x1x1 mesh (single-chip serving / bench path)."""
    if device is None:
        device = jax.devices()[0]
    return build_mesh(MeshPlan(), [device])


def validate_plan_fits_slice(plan: MeshPlan, slice_chips: int) -> None:
    """Gang contract: tp*sp must fit inside one ICI slice.

    dp may cross slices (DCN); tp and sp traffic must stay on ICI; pp
    stage hops are point-to-point activation transfers and may cross
    slices (each stage's tp*sp group must still be slice-resident). The
    orchestrator enforces the pod-placement half of this (slice-atomic
    PodGangs); this checks the in-pod mesh half.
    """
    ici = plan.tp * plan.sp
    if ici > slice_chips:
        raise ValueError(
            f"tp*sp={ici} exceeds slice size {slice_chips}; "
            "sequence/tensor parallel groups must be ICI-resident")
    if slice_chips % ici != 0:
        raise ValueError(
            f"slice size {slice_chips} not divisible by tp*sp={ici}")
