"""Logical→physical sharding rules for the model stack.

Parameters and activations are annotated with *logical* axis names; the
rules below map them onto mesh axes (dp, sp, tp). This keeps model code
free of mesh knowledge — the same model runs single-chip (all rules
collapse to replication) or on a v5e-256 mesh.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from grove_tpu.parallel.mesh import AXIS_DP, AXIS_EP, AXIS_SP, AXIS_TP

# logical axis -> mesh axis (None = replicate; a tuple shards over the
# product of those axes)
LOGICAL_RULES: dict[str, str | tuple[str, ...] | None] = {
    "batch": AXIS_DP,
    "seq": AXIS_SP,          # sequence parallelism for long context
    "vocab": AXIS_TP,
    "embed": None,           # d_model replicated (activations row-sharded by batch)
    "heads": AXIS_TP,        # attention heads over tp
    "kv_heads": AXIS_TP,
    "head_dim": None,
    "mlp": AXIS_TP,          # ffn hidden over tp
    "layers": None,          # scan-stacked layer axis
    # MoE experts: the dedicated ep axis first, tp as the inner factor —
    # on a tp-only mesh (ep=1) experts still shard over tp (a Mixtral's
    # expert weights replicated per device would blow the HBM budget);
    # with ep>1 they shard over ep×tp.
    "expert": (AXIS_EP, AXIS_TP),
}


def logical_pspec(*logical_axes: str | None) -> P:
    """Translate a tuple of logical axis names to a PartitionSpec.

    Unknown names raise (a typo'd axis silently replicating would cost
    N× memory and collectives while still computing correct numbers).
    """
    return P(*[LOGICAL_RULES[a] if a is not None else None
               for a in logical_axes])


def logical_sharding(mesh: Mesh, *logical_axes: str | None) -> NamedSharding:
    return NamedSharding(mesh, logical_pspec(*logical_axes))


# PartitionSpecs per parameter leaf name. Keys match the param pytree
# produced by grove_tpu.models.llama.init_params.
_PARAM_RULES: dict[str, tuple[str | None, ...]] = {
    "tok_embed": ("vocab", "embed"),
    "lm_head": ("embed", "vocab"),
    "final_norm": ("embed",),
    # per-layer (leading stacked "layers" axis added automatically)
    "attn_norm": ("embed",),
    "mlp_norm": ("embed",),
    "wq": ("embed", "heads", "head_dim"),
    "wk": ("embed", "kv_heads", "head_dim"),
    "wv": ("embed", "kv_heads", "head_dim"),
    "wo": ("heads", "head_dim", "embed"),
    "w_gate": ("embed", "mlp"),
    "w_up": ("embed", "mlp"),
    "w_down": ("mlp", "embed"),
    # MoE: experts shard over the dedicated ep axis; router replicated
    "router": ("embed", None),
    "we_gate": ("expert", "embed", None),
    "we_up": ("expert", "embed", None),
    "we_down": ("expert", None, "embed"),
}

_STACKED = {"attn_norm", "mlp_norm", "wq", "wk", "wv", "wo",
            "w_gate", "w_up", "w_down",
            "router", "we_gate", "we_up", "we_down"}


def param_pspec(name: str) -> P:
    """PartitionSpec for a named parameter leaf."""
    logical = _PARAM_RULES[name]
    if name in _STACKED:
        logical = ("layers",) + logical
    return logical_pspec(*logical)


def _path_entry_name(entry) -> str:
    """A tree-path entry's plain name: DictKey carries .key, a
    registered dataclass's GetAttrKey carries .name."""
    if hasattr(entry, "key"):
        return entry.key
    if hasattr(entry, "name"):
        return entry.name
    return str(entry)


def param_pspecs(params: Any) -> Any:
    """A pytree of PartitionSpecs matching ``params`` (dict-of-dict layout).

    The single source of truth for parameter placement — consumed both by
    ``param_shardings`` (device_put) and by shard_map in_specs (e.g. the
    MoE expert-parallel path).

    Quantized trees (serving/quant.QTensor) are handled: the int8 ``q``
    leaf takes its parent weight's spec (same shape), the per-channel
    ``scale`` replicates — its contracted axes are kept as size-1 dims,
    and sharding a size-1 axis over tp>1 is invalid while the bytes are
    negligible anyway.
    """
    def leaf(path, _):
        name = _path_entry_name(path[-1])
        if name in ("q", "scale") and len(path) >= 2:
            return param_pspec(_path_entry_name(path[-2])) \
                if name == "q" else P()
        return param_pspec(name)
    return jax.tree_util.tree_map_with_path(leaf, params)


def param_shardings(mesh: Mesh, params: Any) -> Any:
    """A pytree of NamedShardings matching ``params`` (dict-of-dict layout)."""
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                        param_pspecs(params),
                        is_leaf=lambda x: isinstance(x, P))


def shard_params(mesh: Mesh, params: Any) -> Any:
    """Device-put params with their canonical shardings."""
    return jax.device_put(params, param_shardings(mesh, params))


# ---- paged serving path (GSPMD over the ICI mesh) --------------------
# The PagedDecodeEngine jits every dispatch with explicit NamedSharding
# in/out shardings (the modern GSPMD pattern — jit + NamedSharding, XLA
# inserts the collectives; not pmap). KV blocks shard like the weights
# that produced them: over tp on the kv_heads axis. Token buffers,
# block tables, and lengths are tiny host-fed arrays and replicate — a
# decode batch is one cooperative tp group, not a dp-split workload.
# On a 1-chip mesh (the CPU fallback) every spec collapses to a no-op,
# which is exactly the "same engine, both worlds" contract.


def paged_kv_pspec() -> P:
    """[layers, num_blocks, block_size, n_kv, head_dim] — kv heads
    shard over tp, everything else replicated (blocks are a shared
    pool addressed by table, never a parallel axis)."""
    return logical_pspec("layers", None, None, "kv_heads", "head_dim")


def paged_kv_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, paged_kv_pspec())


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def paged_scale_pspec() -> P:
    """int8-KV dequant scales, [layers, num_blocks, block_size, n_kv] —
    co-sharded with the pools they scale (kv_heads over tp)."""
    return logical_pspec("layers", None, None, "kv_heads")


def paged_scale_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, paged_scale_pspec())


def paged_step_shardings(mesh: Mesh, params: Any,
                         sampled: bool = False,
                         quant: bool = False) -> tuple:
    """(in_shardings, out_shardings) for the paged decode step:
    (params, tokens[b], kv_k, kv_v[, k_scale, v_scale], tables[b,w],
    lengths[b][, key]) → (next[b], kv pools..., lengths[b][, key]).
    ``quant`` inserts the int8 scale pools right after the payload
    pools, matching ``decode_step_paged``'s quantized signature."""
    ps = param_shardings(mesh, params)
    kv = paged_kv_sharding(mesh)
    rep = replicated(mesh)
    pool = (kv, kv, paged_scale_sharding(mesh),
            paged_scale_sharding(mesh)) if quant else (kv, kv)
    ins = (ps, rep) + pool + (rep, rep)
    outs = (rep,) + pool + (rep,)
    if sampled:
        ins += (rep,)
        outs += (rep,)
    return ins, outs


def paged_prefill_shardings(mesh: Mesh, params: Any,
                            quant: bool = False) -> tuple:
    """(in_shardings, out_shardings) for one chunked-prefill window:
    (params, tokens[1,c], kv pools..., table[1,w], offset, logit_idx,
    n_valid) → (logits[1,vocab], kv pools...). The spec list mirrors
    ``models/llama.prefill_chunk_paged``'s full signature — an arity
    drift here surfaces only as a jit error at engine construction,
    so keep them together."""
    ps = param_shardings(mesh, params)
    kv = paged_kv_sharding(mesh)
    rep = replicated(mesh)
    pool = (kv, kv, paged_scale_sharding(mesh),
            paged_scale_sharding(mesh)) if quant else (kv, kv)
    return (ps, rep) + pool + (rep, rep, rep, rep), (rep,) + pool


def paged_handoff_shardings(mesh: Mesh, quant: bool = False) -> tuple:
    """(in_shardings, out_shardings) for the disaggregated block
    handoff copy (``models/llama.paged_block_copy``): (dst pools...,
    src pools..., src_id, dst_id) → (dst pools...). Both pools carry
    the kv-heads-over-tp pspec, so on a sharded mesh the copy is a
    local per-shard move — each chip copies its own head slice, no
    collective (the block axis is never a parallel axis). The source
    pool is NOT donated: the producer keeps serving from it."""
    kv = paged_kv_sharding(mesh)
    rep = replicated(mesh)
    pool = (kv, kv, paged_scale_sharding(mesh),
            paged_scale_sharding(mesh)) if quant else (kv, kv)
    return pool + pool + (rep, rep), pool


def paged_spec_shardings(mesh: Mesh, params: Any, dparams: Any,
                         quant: bool = False,
                         self_draft: bool = False) -> tuple:
    """(in_shardings, out_shardings) for the fused speculative step
    (``models/llama.spec_step_paged``): (params, dparams, tokens[b],
    target pools..., draft pools..., tables[b,w], lengths[b],
    limit[b]) → (out_tokens[b,k+1], next[b], lengths[b], target
    pools..., draft pools...). The draft pool shards exactly like the
    target pool — same kv_heads-over-tp rule, its own (smaller)
    arrays. With ``self_draft`` the drafter runs against the target
    pool, so dparams and the draft pools drop out of the signature on
    both sides."""
    ps = param_shardings(mesh, params)
    kv = paged_kv_sharding(mesh)
    rep = replicated(mesh)
    pool = (kv, kv, paged_scale_sharding(mesh),
            paged_scale_sharding(mesh)) if quant else (kv, kv)
    if self_draft:
        return ((ps, rep) + pool + (rep, rep, rep),
                (rep, rep, rep) + pool)
    dps = param_shardings(mesh, dparams)
    ins = (ps, dps, rep) + pool + (kv, kv) + (rep, rep, rep)
    outs = (rep, rep, rep) + pool + (kv, kv)
    return ins, outs
