"""Pipeline parallelism: GPipe-style microbatch schedule over a pp axis.

Layers are sharded across stages (the leading stacked-layer axis split
over ``pp``); activations flow stage-to-stage via ``lax.ppermute``
(nearest-neighbour ICI hops, like the ring). The schedule runs
M + S - 1 ticks: at tick t, stage s works on microbatch t - s — every
stage executes the same SPMD program with inactivity masked by zeros, so
the bubble costs compute but never diverges control flow (XLA-friendly).

Embedding/head/final-norm weights are replicated across stages; stage 0
embeds, the last stage projects to logits, and the result is summed
across stages (only the last contributes non-zeros).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:                       # moved to the top level in newer jax
    from jax import shard_map as _shard_map
except ImportError:        # jax <= 0.4.x keeps it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map


from grove_tpu.models.llama import LlamaConfig, _layer_prefill, head
from grove_tpu.ops.rope import rope_table
from grove_tpu.parallel.mesh import AXIS_PP, AXIS_TP


def _axis_size(name):
    # lax.axis_size is newer-jax; psum(1, axis) is the classic idiom it
    # replaced and constant-folds to the same static size under shard_map.
    size = getattr(lax, "axis_size", None)
    return size(name) if size is not None else lax.psum(1, name)


def _pcast_varying(x, axes):
    # lax.pcast's varying-type marking exists only in newer jax; the
    # 0.4.x shard_map has no varying types, so identity is exact there.
    pcast = getattr(lax, "pcast", None)
    return pcast(x, axes, to="varying") if pcast is not None else x


def _stage_body(cfg: LlamaConfig, n_micro: int, tp_axis, tok_embed, lm_head,
                final_norm, layers, tokens):
    """Per-stage SPMD body (under shard_map over pp [× tp]).

    layers: this stage's layer shard (leading axis L/S); when ``tp_axis``
    is set, head/ff dims are additionally sharded over tp and the layer
    body psums its output projections over that axis (Megatron-style).
    tokens: full [B, s] (replicated); microbatches split on B.
    """
    s_count = _axis_size(AXIS_PP)
    stage = lax.axis_index(AXIS_PP)
    B, seq = tokens.shape
    mb = B // n_micro
    d = tok_embed.shape[1]
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    positions = jnp.broadcast_to(jnp.arange(seq), (mb, seq))

    def run_stage(x):
        def body(x, lp):
            x, _ = _layer_prefill(cfg, x, lp, cos, sin, positions, 0,
                                  tp_axis=tp_axis)
            return x, None
        x, _ = lax.scan(body, x, layers)
        return x

    fwd_perm = [(i, (i + 1) % s_count) for i in range(s_count)]
    # pvary: fresh buffers must carry the device-varying type to match
    # the loop carry once mixed with per-stage data.
    carry_in = _pcast_varying(jnp.zeros((mb, seq, d), cfg.dtype),
                              (AXIS_PP,))
    outputs = _pcast_varying(jnp.zeros((n_micro, mb, seq, d), cfg.dtype),
                             (AXIS_PP,))

    def tick(t, state):
        carry_in, outputs = state
        my_mb = t - stage
        active = jnp.logical_and(my_mb >= 0, my_mb < n_micro)

        # Stage 0 sources its input by embedding microbatch t.
        emb_idx = jnp.clip(t, 0, n_micro - 1)
        mb_tokens = lax.dynamic_slice_in_dim(tokens, emb_idx * mb, mb, axis=0)
        embedded = tok_embed[mb_tokens].astype(cfg.dtype)
        x_in = jnp.where(stage == 0, embedded, carry_in)

        x_out = jnp.where(active, run_stage(x_in), jnp.zeros_like(x_in))

        # Last stage records its finished microbatch.
        slot = jnp.clip(my_mb, 0, n_micro - 1)
        record = jnp.logical_and(active, stage == s_count - 1)
        outputs = lax.dynamic_update_slice_in_dim(
            outputs,
            jnp.where(record, x_out, lax.dynamic_slice_in_dim(
                outputs, slot, 1, axis=0)[0])[None],
            slot, axis=0)

        carry_next = lax.ppermute(x_out, AXIS_PP, fwd_perm)
        return carry_next, outputs

    _, outputs = lax.fori_loop(0, n_micro + s_count - 1, tick,
                               (carry_in, outputs))

    # Only the last stage holds real outputs; psum broadcasts them, then
    # every stage runs the final-norm + head. Under tp, lm_head is
    # vocab-sharded (Megatron-style) so each tp member computes only its
    # vocab slice — the result stays vocab-sharded on the way out.
    x = outputs.reshape(B, seq, d)
    x = jnp.where(stage == s_count - 1, x, jnp.zeros_like(x))
    x = lax.psum(x, AXIS_PP)
    return head(cfg, {"final_norm": final_norm, "lm_head": lm_head}, x)


# Per-leaf tp sharding of the stacked layer weights (axis after the
# leading layers axis that carries heads/kv_heads/ff). Norms replicate.
_TP_LAYER_SPECS: dict[str, P] = {
    "attn_norm": P(AXIS_PP),
    "mlp_norm": P(AXIS_PP),
    "wq": P(AXIS_PP, None, AXIS_TP, None),
    "wk": P(AXIS_PP, None, AXIS_TP, None),
    "wv": P(AXIS_PP, None, AXIS_TP, None),
    "wo": P(AXIS_PP, AXIS_TP, None, None),
    "w_gate": P(AXIS_PP, None, AXIS_TP),
    "w_up": P(AXIS_PP, None, AXIS_TP),
    "w_down": P(AXIS_PP, AXIS_TP, None),
}


def pipeline_forward(cfg: LlamaConfig, params, tokens: jnp.ndarray,
                     mesh: Mesh, n_microbatches: int = 2) -> jnp.ndarray:
    """Forward pass with layers pipelined over the mesh's ``pp`` axis.

    When the mesh also carries a ``tp`` axis > 1, each stage's layer
    weights are tensor-parallel over it (heads and ff sharded; output
    projections psum over tp inside the stage body) — the composed
    pp×tp execution the orchestrator places as one gang per stage with
    tp ICI-resident within each stage.

    Requires n_layers % pp == 0, batch % n_microbatches == 0, and (for
    tp > 1) n_heads/n_kv_heads/d_ff divisible by tp. The dense-MLP
    Llama param layout is expected (layer-stacked leaves).
    """
    (pp_size,) = (mesh.shape[AXIS_PP],)
    tp_size = dict(mesh.shape).get(AXIS_TP, 1)
    assert cfg.n_layers % pp_size == 0, \
        f"{cfg.n_layers} layers not divisible into {pp_size} stages"
    assert tokens.shape[0] % n_microbatches == 0

    tp_axis = None
    head_spec, out_spec = P(), P()
    if tp_size > 1:
        assert cfg.n_heads % tp_size == 0 and cfg.n_kv_heads % tp_size == 0 \
            and cfg.d_ff % tp_size == 0 and cfg.vocab_size % tp_size == 0, \
            f"heads/kv/ff/vocab not divisible by tp={tp_size}"
        tp_axis = AXIS_TP
        layer_spec = {k: _TP_LAYER_SPECS[k] for k in params["layers"]}
        head_spec = P(None, AXIS_TP)       # lm_head vocab-sharded over tp
        out_spec = P(None, None, AXIS_TP)  # logits stay vocab-sharded
    else:
        layer_spec = jax.tree.map(lambda _: P(AXIS_PP), params["layers"])
    fn = _shard_map(
        partial(_stage_body, cfg, n_microbatches, tp_axis),
        mesh=mesh,
        in_specs=(P(), head_spec, P(), layer_spec, P()),
        out_specs=out_spec,
    )
    return fn(params["tok_embed"], params["lm_head"], params["final_norm"],
              params["layers"], tokens)
