from grove_tpu.parallel.mesh import MeshPlan, build_mesh, mesh_axes_for
from grove_tpu.parallel.sharding import (
    LOGICAL_RULES,
    logical_sharding,
    param_pspec,
    shard_params,
)

__all__ = [
    "MeshPlan",
    "build_mesh",
    "mesh_axes_for",
    "LOGICAL_RULES",
    "logical_sharding",
    "param_pspec",
    "shard_params",
]
