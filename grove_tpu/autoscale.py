"""Multi-level autoscaling — the HPA analog (C1e).

The reference creates one HPA per auto-scaled target's scale subresource
(podcliqueset/components/hpa/). This control plane owns the loop: a
MetricsRegistry holds current metric values (pushed by serving engines —
e.g. queue depth per clique — or by tests), and the Autoscaler runnable
applies the standard HPA formula

    desired = clamp(ceil(value / target), min_replicas, max_replicas)

at all three levels:

- PodClique — pods within a role,
- PodCliqueScalingGroup — whole model instances (each a gang on a slice),
- PodCliqueSet — whole-service replicas (multislice DP over DCN).

The gang floor: for PCLQ/PCSG, min_replicas is validated to be >=
min_available, so scaling never undercuts the gang guarantee (a PCS has
no floor beyond min_replicas >= 1).
"""

from __future__ import annotations

import math
import threading
import time

from grove_tpu.api import PodClique, PodCliqueScalingGroup, PodCliqueSet
from grove_tpu.runtime.errors import GroveError
from grove_tpu.runtime.logger import get_logger
from grove_tpu.store.client import Client


class MetricsRegistry:
    """Named metric values per (kind, namespace, name): the metrics-server
    analog.

    Multi-reporter aware: each reporting pod/engine contributes its own
    sample and ``get`` returns the SUM of fresh samples (queue-depth-style
    metrics represent per-reporter load; the total drives scaling).
    Last-write-wins across reporters would flap the autoscaler whenever
    load is heterogeneous. Samples expire after ``sample_ttl`` so dead
    reporters stop counting.
    """

    def __init__(self, sample_ttl: float = 10.0) -> None:
        self._lock = threading.Lock()
        self.sample_ttl = sample_ttl
        self._samples: dict[tuple[str, str, str, str],
                            dict[str, tuple[float, float]]] = {}

    def set(self, kind: str, name: str, metric: str, value: float,
            namespace: str = "default", reporter: str = "_default") -> None:
        import time as _time
        key = (kind, namespace, name, metric)
        with self._lock:
            self._samples.setdefault(key, {})[reporter] = (value, _time.time())

    def get(self, kind: str, name: str, metric: str,
            namespace: str = "default") -> float | None:
        import time as _time
        key = (kind, namespace, name, metric)
        cutoff = _time.time() - self.sample_ttl
        with self._lock:
            samples = self._samples.get(key)
            if not samples:
                return None
            for reporter in [r for r, (_, ts) in samples.items()
                             if ts < cutoff]:
                del samples[reporter]
            if not samples:
                return None
            return sum(v for v, _ in samples.values())


def desired_replicas(value: float, target: float, lo: int, hi: int) -> int:
    if target <= 0:
        return lo
    return max(lo, min(hi, math.ceil(value / target)))


class Autoscaler:
    def __init__(self, client: Client, metrics: MetricsRegistry,
                 namespace: str | None = None, sync_period: float = 1.0,
                 scale_down_stabilization: float = 30.0):
        """``namespace=None`` scans every namespace (the default: the rest
        of the control plane is namespace-agnostic too).

        ``scale_down_stabilization``: scale-down uses the MAX desired
        value observed over this window (the k8s HPA downscale
        stabilization) — a noisy queue-depth signal must not thrash
        replicas, because every PCSG flap is a gang create/destroy.
        Scale-UP stays immediate (starving traffic to look smooth is the
        wrong trade).
        """
        self.client = client
        self.metrics = metrics
        self.namespace = namespace
        self.sync_period = sync_period
        self.scale_down_stabilization = scale_down_stabilization
        self.log = get_logger("autoscaler")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # (kind, namespace, name) -> [(timestamp, desired)] recent history
        self._history: dict[tuple[str, str, str],
                            list[tuple[float, int]]] = {}

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="autoscaler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._pass()
            except Exception:  # noqa: BLE001 - loop survival
                self.log.exception("autoscale pass panicked")
            self._stop.wait(self.sync_period)

    def _pass(self) -> None:
        live_keys: set[tuple[str, str, str]] = set()
        for kind_cls in (PodClique, PodCliqueScalingGroup, PodCliqueSet):
            for obj in self.client.list(kind_cls, self.namespace):
                a = obj.spec.auto_scaling
                if a is None or obj.meta.deletion_timestamp is not None:
                    continue
                live_keys.add((obj.KIND, obj.meta.namespace, obj.meta.name))
                value = self.metrics.get(obj.KIND, obj.meta.name, a.metric,
                                         namespace=obj.meta.namespace)
                if value is None:
                    continue
                # min_replicas is filled by defaulting admission for
                # template-declared configs; an un-admitted object
                # (direct construction) floors at 1.
                want = desired_replicas(value, a.target_value,
                                        a.min_replicas or 1, a.max_replicas)
                want = self._stabilized(obj, want)
                if want != obj.spec.replicas:
                    self.log.info("scaling %s/%s %d -> %d (%s=%.2f)",
                                  obj.KIND, obj.meta.name, obj.spec.replicas,
                                  want, a.metric, value)
                    obj.spec.replicas = want
                    try:
                        self.client.update(obj)
                    except GroveError:
                        pass  # conflict: next pass retries on fresh state
        # Evict history of deleted objects: unbounded growth under churn,
        # and a recreated same-name object must not inherit a dead
        # object's spike window.
        for key in [k for k in self._history if k not in live_keys]:
            del self._history[key]

    def _stabilized(self, obj, want: int) -> int:
        """HPA downscale stabilization: record the raw desired value and
        return max(desired over the window) when shrinking — scale-down
        happens only after the signal has stayed low for the whole
        window; scale-up passes through untouched."""
        now = time.time()
        key = (obj.KIND, obj.meta.namespace, obj.meta.name)
        window = self._history.setdefault(key, [])
        window.append((now, want))
        cutoff = now - self.scale_down_stabilization
        while window and window[0][0] < cutoff:
            window.pop(0)
        if want >= obj.spec.replicas:
            return want
        return min(obj.spec.replicas, max(w for _, w in window))
