"""Multi-level autoscaling — the HPA analog (C1e).

The reference creates one HPA per auto-scaled target's scale subresource
(podcliqueset/components/hpa/). This control plane owns the loop: a
MetricsRegistry holds current metric values (pushed by serving engines —
e.g. queue depth per clique — or by tests), and the Autoscaler runnable
applies the standard HPA formula

    desired = clamp(ceil(value / target), min_replicas, max_replicas)

at all three levels:

- PodClique — pods within a role,
- PodCliqueScalingGroup — whole model instances (each a gang on a slice),
- PodCliqueSet — whole-service replicas (multislice DP over DCN).

The gang floor: for PCLQ/PCSG, min_replicas is validated to be >=
min_available, so scaling never undercuts the gang guarantee (a PCS has
no floor beyond min_replicas >= 1).
"""

from __future__ import annotations

import math
import threading
import time

from grove_tpu.api import PodClique, PodCliqueScalingGroup, PodCliqueSet
from grove_tpu.runtime.errors import ConflictError, GroveError
from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.metrics import GLOBAL_METRICS
from grove_tpu.store.client import Client


# Metric-name hints for the default aggregation mode. Load signals
# (queue depth, rps, token counts) SUM across reporters — the total
# drives scaling. Latency-style signals must NOT: a 2-replica PCSG
# summing its engines' TTFT would double its apparent latency, so
# worst-case percentiles take the MAX and utilization-style fractions
# AVERAGE. An explicit per-sample ``agg`` (the batched push carries
# one) always wins over the name hint.
_LATENCY_HINTS = ("ttft", "tpot", "latency")


def default_agg(metric: str) -> str:
    m = metric.lower()
    if "util" in m:
        return "avg"
    if (any(h in m for h in _LATENCY_HINTS) or m.endswith("_ms")
            or m.endswith("_seconds")):
        return "max"
    return "sum"


class MetricsRegistry:
    """Named metric values per (kind, namespace, name): the metrics-server
    analog.

    Multi-reporter aware: each reporting pod/engine contributes its own
    sample, and ``get`` combines fresh samples per the metric's
    aggregation mode — SUM for load signals (queue depth: per-reporter
    load, the total drives scaling), MAX for worst-case latencies (a
    2-replica PCSG's p99 TTFT is its worst replica's, never the sum),
    AVG for utilization fractions. Last-write-wins across reporters
    would flap the autoscaler whenever load is heterogeneous. Samples
    expire after ``sample_ttl`` so dead reporters stop counting.
    """

    def __init__(self, sample_ttl: float = 10.0) -> None:
        self._lock = threading.Lock()
        self.sample_ttl = sample_ttl
        # key -> reporter -> (value, ts, agg-mode-at-set-time)
        self._samples: dict[tuple[str, str, str, str],
                            dict[str, tuple[float, float, str]]] = {}

    def set(self, kind: str, name: str, metric: str, value: float,
            namespace: str = "default", reporter: str = "_default",
            agg: str | None = None) -> None:
        """``agg`` (sum|max|avg) pins how this metric combines across
        reporters; None infers from the metric name (default_agg)."""
        import time as _time
        if agg not in (None, "sum", "max", "avg"):
            raise ValueError(f"unknown aggregation mode {agg!r}")
        key = (kind, namespace, name, metric)
        with self._lock:
            self._samples.setdefault(key, {})[reporter] = (
                value, _time.time(), agg or default_agg(metric))

    @staticmethod
    def _combine(values: list[float], agg: str) -> float:
        if agg == "max":
            return max(values)
        if agg == "avg":
            return sum(values) / len(values)
        return sum(values)

    @staticmethod
    def _aggregate_locked(samples: dict, cutoff: float,
                          ) -> tuple[float, str, int] | None:
        """Expire stale reporters in place, then combine what's fresh:
        (value, agg mode, reporter count), or None when nothing is
        fresh. The ONE implementation of multi-reporter aggregation —
        get_with_mode (the Autoscaler's read) and all_fresh (the
        ServingObserver's scrape) must never disagree on a series.
        Caller holds the registry lock. The newest sample's mode wins
        (reporters agree in practice; a rolling update changing the
        mode converges as old samples expire)."""
        for reporter in [r for r, (_, ts, _a) in samples.items()
                         if ts < cutoff]:
            del samples[reporter]
        if not samples:
            return None
        agg = max(samples.values(), key=lambda s: s[1])[2]
        return (MetricsRegistry._combine(
            [v for v, _, _a in samples.values()], agg), agg, len(samples))

    def get(self, kind: str, name: str, metric: str,
            namespace: str = "default") -> float | None:
        got = self.get_with_mode(kind, name, metric, namespace)
        return None if got is None else got[0]

    def get_with_mode(self, kind: str, name: str, metric: str,
                      namespace: str = "default",
                      ) -> tuple[float, str, int] | None:
        """(aggregated value, mode, fresh reporter count) — the
        autoscaler picks its scaling law off the mode (a max/avg signal
        is a latency target, not a per-reporter load to divide)."""
        import time as _time
        key = (kind, namespace, name, metric)
        cutoff = _time.time() - self.sample_ttl
        with self._lock:
            samples = self._samples.get(key)
            if not samples:
                return None
            return self._aggregate_locked(samples, cutoff)

    def all_fresh(self) -> list[tuple[str, str, str, str, float, str, int]]:
        """Every fresh series: (kind, namespace, name, metric, value,
        agg, reporters). The ServingObserver's scrape surface — one
        locked pass, expiring stale reporters as it goes."""
        import time as _time
        cutoff = _time.time() - self.sample_ttl
        out = []
        with self._lock:
            for key in list(self._samples):
                got = self._aggregate_locked(self._samples[key], cutoff)
                if got is None:
                    del self._samples[key]
                    continue
                kind, namespace, name, metric = key
                out.append((kind, namespace, name, metric, *got))
        return out


def desired_replicas(value: float, target: float, lo: int, hi: int) -> int:
    if target <= 0:
        return lo
    return max(lo, min(hi, math.ceil(value / target)))


# A latency signal well under target means capacity to spare: decay one
# replica only when the aggregated signal sits below this fraction of
# the target (hysteresis — a p99 hovering AT target must neither grow
# nor shrink the fleet).
LATENCY_DECAY_FRACTION = 0.5


def desired_replicas_latency(value: float, target: float, current: int,
                             lo: int, hi: int) -> int:
    """Step controller for latency-target metrics (p99 TTFT et al).

    The HPA ratio formula assumes the signal divides across replicas —
    true for queue depth, false for a percentile (2x replicas does not
    halve p99 TTFT, and ceil(ttft/target) would jump straight to the
    ratio). Latency scaling is therefore incremental: breach → one step
    out (next pass breaches again if one step wasn't enough), well
    under target → one step in (downscale stabilization still applies
    on top)."""
    if target <= 0:
        return max(lo, min(hi, current))
    if value > target:
        want = current + 1
    elif value < target * LATENCY_DECAY_FRACTION:
        want = current - 1
    else:
        want = current
    return max(lo, min(hi, want))


class Autoscaler:
    def __init__(self, client: Client, metrics: MetricsRegistry,
                 namespace: str | None = None, sync_period: float = 1.0,
                 scale_down_stabilization: float = 30.0):
        """``namespace=None`` scans every namespace (the default: the rest
        of the control plane is namespace-agnostic too).

        ``scale_down_stabilization``: scale-down uses the MAX desired
        value observed over this window (the k8s HPA downscale
        stabilization) — a noisy queue-depth signal must not thrash
        replicas, because every PCSG flap is a gang create/destroy.
        Scale-UP stays immediate (starving traffic to look smooth is the
        wrong trade).
        """
        self.client = client
        self.metrics = metrics
        self.namespace = namespace
        self.sync_period = sync_period
        self.scale_down_stabilization = scale_down_stabilization
        self.log = get_logger("autoscaler")
        # Decision events (ScaledUp/ScaledDown with signal vs target):
        # the kubectl-describe trail for "why did my fleet grow".
        from grove_tpu.runtime.events import EventRecorder
        self.events = EventRecorder(client, "autoscaler")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # (kind, namespace, name) -> [(timestamp, desired)] recent history
        self._history: dict[tuple[str, str, str],
                            list[tuple[float, int]]] = {}

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="autoscaler",
                                        daemon=True)
        self._thread.start()

    def request_stop(self) -> None:
        """Signal-only phase of the manager's two-phase shutdown."""
        self._stop.set()

    def stop(self) -> None:
        self.request_stop()
        if self._thread is not None:
            # A sync pass landing after stop() would write scale
            # decisions into a store mid-teardown (the runnable
            # contract, grovelint thread-join-in-stop).
            self._thread.join(timeout=2.0)
            self._thread = None

    def pause(self) -> None:
        """Leadership parking (grove_tpu/ha): a demoted replica's scale
        writes would be fenced anyway; pausing spares the error noise
        and the registry churn."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def _run(self) -> None:
        while not self._stop.is_set():
            if getattr(self, "_paused", False):
                self._stop.wait(self.sync_period)
                continue
            try:
                self._pass()
            except Exception:  # noqa: BLE001 - loop survival
                self.log.exception("autoscale pass panicked")
            self._stop.wait(self.sync_period)

    def _pass(self) -> None:
        live_keys: set[tuple[str, str, str]] = set()
        desired_series: list[tuple[dict, float]] = []
        for kind_cls in (PodClique, PodCliqueScalingGroup, PodCliqueSet):
            for obj in self.client.list(kind_cls, self.namespace):
                a = obj.spec.auto_scaling
                if a is None or obj.meta.deletion_timestamp is not None:
                    continue
                live_keys.add((obj.KIND, obj.meta.namespace, obj.meta.name))
                got = self.metrics.get_with_mode(
                    obj.KIND, obj.meta.name, a.metric,
                    namespace=obj.meta.namespace)
                if got is None:
                    desired_series.append(
                        ({"kind": obj.KIND,
                          "namespace": obj.meta.namespace,
                          "name": obj.meta.name},
                         float(obj.spec.replicas)))
                    continue
                value, agg, _reporters = got
                # min_replicas is filled by defaulting admission for
                # template-declared configs; an un-admitted object
                # (direct construction) floors at 1.
                lo, hi = a.min_replicas or 1, a.max_replicas
                if agg in ("max", "avg"):
                    # Latency-target signal (p99 TTFT vs an SLO): step
                    # scaling, not the ratio formula — see
                    # desired_replicas_latency.
                    want = desired_replicas_latency(
                        value, a.target_value, obj.spec.replicas, lo, hi)
                else:
                    want = desired_replicas(value, a.target_value, lo, hi)
                want = self._stabilized(obj, want)
                desired_series.append(
                    ({"kind": obj.KIND,
                      "namespace": obj.meta.namespace,
                      "name": obj.meta.name},
                     float(want)))
                if want != obj.spec.replicas:
                    old = obj.spec.replicas
                    self.log.info("scaling %s/%s %d -> %d (%s=%.2f)",
                                  obj.KIND, obj.meta.name, old,
                                  want, a.metric, value)
                    obj.spec.replicas = want  # grovelint: disable=clone-before-mutate -- autoscaler lists through the DIRECT leader client (never the informer cache): store lists return per-call clones, safe to edit
                    try:
                        self.client.update(obj)
                    except ConflictError:
                        # Raced another writer: the next pass retries on
                        # fresh state. Counted, not swallowed — a
                        # sustained rate means something else fights
                        # the autoscaler over replicas.
                        GLOBAL_METRICS.inc(
                            "grove_autoscaler_conflicts_total",
                            kind=obj.KIND,
                            namespace=obj.meta.namespace,
                            name=obj.meta.name)
                        continue
                    except GroveError as e:
                        GLOBAL_METRICS.inc(
                            "grove_autoscaler_conflicts_total",
                            kind=obj.KIND,
                            namespace=obj.meta.namespace,
                            name=obj.meta.name)
                        self.log.warning("scale %s/%s rejected: %s",
                                         obj.KIND, obj.meta.name, e)
                        continue
                    GLOBAL_METRICS.inc(
                        "grove_autoscaler_decisions_total",
                        kind=obj.KIND,
                        direction="up" if want > old else "down")
                    self.events.event(
                        obj, "Normal",
                        "ScaledUp" if want > old else "ScaledDown",
                        f"{a.metric}={value:.2f} ({agg}) vs target "
                        f"{a.target_value:g}: replicas {old} -> {want}")
        # Gauge-family semantics: desired replicas per autoscaled
        # object, zeroed when the object drains (a deleted PCSG must
        # not report its last desired count forever).
        GLOBAL_METRICS.set_gauge_family("grove_autoscaler_desired_replicas",
                                        desired_series)
        # Evict history of deleted objects: unbounded growth under churn,
        # and a recreated same-name object must not inherit a dead
        # object's spike window.
        for key in [k for k in self._history if k not in live_keys]:
            del self._history[key]

    def _stabilized(self, obj, want: int) -> int:
        """HPA downscale stabilization: record the raw desired value and
        return max(desired over the window) when shrinking — scale-down
        happens only after the signal has stayed low for the whole
        window; scale-up passes through untouched."""
        now = time.time()
        key = (obj.KIND, obj.meta.namespace, obj.meta.name)
        window = self._history.setdefault(key, [])
        window.append((now, want))
        cutoff = now - self.scale_down_stabilization
        while window and window[0][0] < cutoff:
            window.pop(0)
        if want >= obj.spec.replicas:
            return want
        return min(obj.spec.replicas, max(w for _, w in window))
