"""Node agents: run (or synthesise) the pods bound to TPU hosts.

FakeKubeletPool is the KWOK analog (SURVEY.md §4): one thread services
every fake node, transitioning bound pods Pending → Running (+Ready)
once their startup barrier clears — no processes run, so the control
plane can be exercised at 1000-pod scale on one machine. The real
subprocess-running agent lives in grove_tpu.agent.process.
"""

from __future__ import annotations

import threading
import time

from grove_tpu.api import Node, Pod, constants as c
from grove_tpu.api.core import PodPhase
from grove_tpu.api.meta import Condition, set_condition
from grove_tpu.agent.barrier import barrier_satisfied
from grove_tpu.runtime.errors import GroveError
from grove_tpu.runtime.logger import get_logger
from grove_tpu.store.client import Client


class FakeKubeletPool:
    """Synthetic readiness for all fake nodes (KWOK analog)."""

    def __init__(self, client: Client, namespace: str | None = None,
                 tick: float = 0.05, startup_latency: float = 0.0):
        self.client = client
        self.namespace = namespace
        self.tick = tick
        self.startup_latency = startup_latency
        self.log = get_logger("agent.fake")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="fake-kubelet",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._pass()
            except Exception:  # noqa: BLE001 - agent survival barrier
                self.log.exception("fake kubelet pass panicked")
            time.sleep(self.tick)

    def _fake_nodes(self) -> set[str]:
        return {n.meta.name for n in self.client.list(Node, self.namespace)
                if n.spec.fake}

    def _pass(self) -> None:
        fake_nodes = self._fake_nodes()
        if not fake_nodes:
            return
        for pod in self.client.list(Pod, self.namespace):
            if (pod.status.node_name in fake_nodes
                    and pod.status.phase == PodPhase.PENDING
                    and pod.meta.deletion_timestamp is None):
                if not barrier_satisfied(self.client, pod.spec.startup_barrier,
                                         pod.meta.namespace):
                    continue
                if self.startup_latency:
                    time.sleep(self.startup_latency)
                pod.status.phase = PodPhase.RUNNING
                pod.status.start_time = time.time()
                pod.status.pod_ip = f"10.0.{hash(pod.meta.name) % 250}.{hash(pod.meta.uid) % 250}"
                pod.status.conditions = set_condition(
                    pod.status.conditions,
                    Condition(type=c.COND_READY, status="True",
                              reason="FakeNodeReady"))
                try:
                    self.client.update_status(pod)
                except GroveError:
                    pass  # retried next pass


def fail_pod(client: Client, name: str, namespace: str = "default",
             message: str = "injected failure") -> None:
    """Test/chaos helper: mark a pod failed (node crash analog)."""
    pod = client.get(Pod, name, namespace)
    pod.status.phase = PodPhase.FAILED
    pod.status.message = message
    pod.status.conditions = set_condition(
        pod.status.conditions,
        Condition(type=c.COND_READY, status="False", reason="Failed",
                  message=message))
    client.update_status(pod)
