"""Node agents: run (or synthesise) the pods bound to TPU hosts.

FakeKubeletPool is the KWOK analog (SURVEY.md §4): one thread services
every fake node, transitioning bound pods Pending → Running (+Ready)
once their startup barrier clears — no processes run, so the control
plane can be exercised at 1000-pod scale on one machine. The real
subprocess-running agent lives in grove_tpu.agent.process.
"""

from __future__ import annotations

import threading
import time

from grove_tpu.api import Node, Pod, constants as c
from grove_tpu.api.core import PodPhase
from grove_tpu.api.meta import Condition, set_condition, trace_id_of
from grove_tpu.agent.barrier import barrier_satisfied
from grove_tpu.runtime.errors import GroveError
from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.trace import GLOBAL_TRACER
from grove_tpu.store.client import Client


class FakeKubeletPool:
    """Synthetic readiness for all fake nodes (KWOK analog)."""

    def __init__(self, client: Client, namespace: str | None = None,
                 tick: float = 0.05, startup_latency: float = 0.0):
        self.client = client
        self.namespace = namespace
        self.tick = tick
        self.startup_latency = startup_latency
        self.log = get_logger("agent.fake")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._nodes_cache: tuple[float, set[str]] = (0.0, set())
        # First-blocked timestamp per pod held at its startup barrier:
        # when the barrier finally clears, the whole wait becomes one
        # agent.barrier_wait span (pruned each pass against the live
        # pending set, so deleted pods cannot leak entries).
        self._blocked_since: dict[tuple[str, str], float] = {}

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="fake-kubelet",
                                        daemon=True)
        self._thread.start()

    def request_stop(self) -> None:
        """Signal-only phase of the manager's two-phase shutdown."""
        self._stop.set()

    def stop(self) -> None:
        self.request_stop()
        if self._thread is not None:
            # Bounded join: an unjoined kubelet pass outlives shutdown
            # and races teardown's store mutations (the runnable
            # contract, grovelint thread-join-in-stop).
            self._thread.join(timeout=2.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._pass()
            except Exception:  # noqa: BLE001 - agent survival barrier
                self.log.exception("fake kubelet pass panicked")
            time.sleep(self.tick)

    def _fake_nodes(self) -> set[str]:
        # Short-TTL cache: the list itself is cheap, but each one QUEUES
        # on the store lock that deploy-time writers are holding —
        # profiled at 1000 pods, a per-tick node list roughly doubled
        # time-to-scheduled through lock contention alone. 0.25s bounds
        # the staleness window for a node whose spec.fake just flipped
        # (chaos handing a node to a real kubelet) to a few ticks, far
        # under the node-lifecycle grace that acts on it.
        ts, names = self._nodes_cache
        now = time.monotonic()      # wall-clock steps must not stretch
        if now - ts > 0.25:         # the documented staleness bound
            names = {n.meta.name
                     for n in self.client.list(Node, self.namespace)
                     if n.spec.fake}
            self._nodes_cache = (now, names)
        return names

    def _pass(self) -> None:
        fake_nodes = self._fake_nodes()
        if not fake_nodes:
            return
        # Field-filtered list: at steady state there are no Pending
        # pods, so the tick clones NOTHING instead of the whole fleet.
        flipped = []
        pending_keys: set[tuple[str, str]] = set()
        for pod in self.client.list(
                Pod, self.namespace,
                fields={"phase": PodPhase.PENDING.value}):
            if (pod.status.node_name in fake_nodes
                    and pod.meta.deletion_timestamp is None):
                key = (pod.meta.namespace, pod.meta.name)
                pending_keys.add(key)
                if not barrier_satisfied(self.client, pod.spec.startup_barrier,
                                         pod.meta.namespace):
                    self._blocked_since.setdefault(key, time.time())
                    continue
                t_start = time.time()
                if self.startup_latency:
                    time.sleep(self.startup_latency)
                pod.status.phase = PodPhase.RUNNING
                pod.status.start_time = time.time()
                pod.status.pod_ip = f"10.0.{hash(pod.meta.name) % 250}.{hash(pod.meta.uid) % 250}"
                pod.status.conditions = set_condition(
                    pod.status.conditions,
                    Condition(type=c.COND_READY, status="True",
                              reason="FakeNodeReady"))
                flipped.append((pod, t_start, key))
        if flipped:
            # One locked batch (KWOK flips whole fleets at once):
            # controllers coalesce the burst instead of N wake-ups;
            # conflict/not-found races resolve as per-item results and
            # retry next pass. An admission denial raises out of the
            # batch (store semantics: systemic failures are loud) — fall
            # back to per-pod writes so one poison pod can't block the
            # pods sorted after it forever.
            pods = [pod for pod, _, _ in flipped]
            try:
                results = self.client.update_status_many(pods)
            except GroveError:
                results = []
                for pod in pods:
                    try:
                        self.client.update_status(pod)
                        results.append(None)
                    except GroveError as e:
                        results.append(e)  # isolated; retried next pass
            # Spans + the gang 'started' milestone only for COMMITTED
            # starts: a conflict-dropped write means the pod is still
            # Pending — recording would pin a false milestone and lose
            # the barrier-wait span for the retry.
            for (pod, t_start, key), err in zip(flipped, results):
                if err is None:
                    record_pod_start_spans(
                        pod, t_start, self._blocked_since.pop(key, None))
        # Only pods still pending can be waiting at a barrier.
        self._blocked_since = {k: v for k, v in self._blocked_since.items()
                               if k in pending_keys}


def record_pod_start_spans(pod, t_start: float,
                           blocked_since: float | None) -> None:
    """Trace the agent-start phase of a pod's lifecycle: an
    ``agent.start`` span for the start action itself, an
    ``agent.barrier_wait`` span covering the whole time the pod sat at
    its startup-ordering barrier, and the gang's ``started`` milestone
    (first pod start wins). Shared by the fake kubelet pool and the
    process kubelet — one span vocabulary for both agent shapes."""
    trace_id = trace_id_of(pod)
    if not trace_id:
        return
    now = time.time()
    if blocked_since is not None:
        GLOBAL_TRACER.record_span(
            "agent.barrier_wait", trace_id, blocked_since, t_start,
            attrs={"pod": pod.meta.name})
    GLOBAL_TRACER.record_span(
        "agent.start", trace_id, t_start, now,
        attrs={"pod": pod.meta.name,
               "node": pod.status.node_name or ""})
    gang = pod.meta.labels.get(c.LABEL_PODGANG_NAME, "")
    if gang:
        GLOBAL_TRACER.milestone(
            trace_id, f"{pod.meta.namespace}/{gang}", "started", ts=now)


def fail_pod(client: Client, name: str, namespace: str = "default",
             message: str = "injected failure") -> None:
    """Test/chaos helper: mark a pod failed (node crash analog)."""
    pod = client.get(Pod, name, namespace)
    pod.status.phase = PodPhase.FAILED
    pod.status.message = message
    pod.status.conditions = set_condition(
        pod.status.conditions,
        Condition(type=c.COND_READY, status="False", reason="Failed",
                  message=message))
    client.update_status(pod)
