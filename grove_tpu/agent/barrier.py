"""Startup-order barrier — the grove-initc analog (I1).

The reference injects an init container that watches sibling pods and
blocks until every parent PodClique has >= minAvailable Ready pods
(initc/internal/wait.go:109-274). Here the same predicate is evaluated
by the node agent before it starts (fake: marks Running) the workload
process; the real agent also re-checks before exec'ing the payload.
"""

from __future__ import annotations

from grove_tpu.api import PodClique
from grove_tpu.api.core import StartupBarrier
from grove_tpu.runtime.errors import NotFoundError
from grove_tpu.store.client import Client


def barrier_satisfied(client: Client, barrier: StartupBarrier | None,
                      namespace: str = "default") -> bool:
    if barrier is None or not barrier.parent_cliques:
        return True
    for fqn in barrier.parent_cliques:
        try:
            parent = client.get(PodClique, fqn, namespace)
        except NotFoundError:
            return False
        # Pinned threshold if the pod builder recorded one; otherwise the
        # parent's live min_available (the parent PCLQ may not have existed
        # at pod-build time — a stale default of 1 would let children jump
        # the barrier).
        need = barrier.min_available.get(fqn, parent.spec.min_available)
        if parent.status.ready_replicas < need:
            return False
    return True
