"""Process-running node agent: pods become real OS processes.

The kubelet analog for real deployments (and the richest e2e tier): pods
bound to non-fake nodes are exec'd with the full injected environment
(GROVE_* identity, TPU_WORKER_ID/TPU_WORKER_HOSTNAMES, slice metadata from
the node's labels). The startup barrier (grove-initc analog, I1) is
enforced before exec — the process only starts once every parent
PodClique has >= min_available Ready pods. Exit code 0 → Succeeded,
non-zero → Failed (which the PodClique controller self-heals by
recreating the pod at the same index).

One ProcessKubelet serves every real node in the cluster — in a true
multi-host deployment each host runs one with ``node_name`` pinned.
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time

from grove_tpu.agent.barrier import barrier_satisfied
from grove_tpu.api import Node, Pod, constants as c
from grove_tpu.api.core import PodPhase
from grove_tpu.api.meta import Condition, set_condition
from grove_tpu.runtime.errors import GroveError, NotFoundError
from grove_tpu.runtime.logger import get_logger
from grove_tpu.store.client import Client


class ProcessKubelet:
    def __init__(self, client: Client, namespace: str | None = None,
                 node_name: str | None = None, tick: float = 0.05,
                 workdir: str | None = None, log_dir: str | None = None,
                 extra_env: dict[str, str] | None = None,
                 wake: threading.Event | None = None):
        self.client = client
        self.namespace = namespace
        self.node_name = node_name
        self.tick = tick
        # Optional wake signal: when set, the loop re-passes immediately
        # instead of waiting out the tick (the remote agent's watch feed
        # sets it on relevant events, so tick can be a slow fallback).
        self.wake = wake
        self.workdir = workdir
        # Agent-level env for every pod (e.g. GROVE_CONTROL_PLANE in serve
        # mode). Read at launch time, so the dict may be filled after
        # construction (the API server's port resolves late).
        self.extra_env = extra_env if extra_env is not None else {}
        # Pod logs (kubectl-logs analog): one file per pod incarnation
        # (name + uid — a self-healed replacement gets its own file).
        self.log_dir = log_dir or os.path.join(
            workdir or os.getcwd(), "pod-logs")
        self.log = get_logger("agent.process")
        # (namespace, pod name) -> (pod uid, proc): the uid detects
        # delete+recreate under the same name within one tick (rolling
        # updates) so a stale process is never adopted; the namespace in
        # the key keeps same-named pods in different namespaces apart.
        self._procs: dict[tuple[str, str], tuple[str, subprocess.Popen]] = {}
        self._last_probe: dict[tuple[str, str], float] = {}
        # First-blocked ts per pod held at its startup barrier — the
        # agent.barrier_wait trace span (see agent/node.py).
        self._blocked_since: dict[tuple[str, str], float] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="process-kubelet", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self.wake is not None:
            self.wake.set()  # unblock a waiting loop promptly
        if self._thread is not None:
            self._thread.join(2.0)
        for key, (_, proc) in list(self._procs.items()):
            self._terminate(key, proc)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._pass()
            except Exception:  # noqa: BLE001 - agent survival barrier
                self.log.exception("process kubelet pass panicked")
            if self.wake is not None:
                self.wake.wait(self.tick)
                self.wake.clear()
            else:
                time.sleep(self.tick)

    def _my_nodes(self) -> dict[str, Node]:
        nodes = {}
        for n in self.client.list(Node, self.namespace):
            if n.spec.fake:
                continue
            if self.node_name is not None and n.meta.name != self.node_name:
                continue
            nodes[n.meta.name] = n
        return nodes

    def _pass(self) -> None:
        nodes = self._my_nodes()
        if not nodes:
            return
        live_pods = {(p.meta.namespace, p.meta.name): p
                     for p in self.client.list(Pod, self.namespace)
                     if p.status.node_name in nodes}

        # Reap: processes whose pod vanished or was replaced (same name,
        # new uid); exited processes.
        reaped: set[tuple[str, str]] = set()
        for key, (uid, proc) in list(self._procs.items()):
            pod = live_pods.get(key)
            if pod is None or pod.meta.deletion_timestamp is not None \
                    or pod.meta.uid != uid:
                self._terminate(key, proc)
                continue
            code = proc.poll()
            if code is not None:
                del self._procs[key]
                self._last_probe.pop(key, None)
                self._set_exit_status(pod, code)
                reaped.add(key)
                continue
            if self._probe_readiness(pod):
                reaped.add(key)    # probe-timeout FAILED: keep the
                # orphan pass from stomping the ProbeTimeout status

        # Orphans: a RUNNING pod on my node with no process entry means
        # its process belonged to a previous agent incarnation (or its
        # exit-status write was lost) — the process is gone either way.
        # Fail it so the standard self-heal recreates it; critical for
        # persistent-state restarts (store/persist.py), where pods
        # survive the reboot but their processes do not.
        # (skip pods reaped THIS pass: live_pods is a pre-reap snapshot,
        # so they still read RUNNING here and the orphan write would
        # stomp their just-written exit status.)
        for key, pod in live_pods.items():
            if (pod.status.phase == PodPhase.RUNNING
                    and key not in self._procs
                    and key not in reaped
                    and pod.meta.deletion_timestamp is None):
                def orphaned(p: Pod) -> None:
                    if p.status.phase != PodPhase.RUNNING:
                        return  # raced a fresher write; no-op suppressed
                    p.status.phase = PodPhase.FAILED
                    p.status.message = "process lost (agent restart)"
                self._write_status(pod, orphaned)
                self.log.warning("pod %s/%s: orphaned (no process); "
                                 "failing for self-heal", *key)

        # Launch: bound pending pods whose barrier cleared.
        from grove_tpu.agent.node import record_pod_start_spans
        for key, pod in live_pods.items():
            if (pod.status.phase != PodPhase.PENDING
                    or key in self._procs
                    or pod.meta.deletion_timestamp is not None):
                continue
            if not barrier_satisfied(self.client, pod.spec.startup_barrier,
                                     pod.meta.namespace):
                self._blocked_since.setdefault(key, time.time())
                continue
            t_start = time.time()
            self._launch(pod, nodes[pod.status.node_name])
            record_pod_start_spans(pod, t_start,
                                   self._blocked_since.pop(key, None))
        # Only pending pods can be barrier-blocked; prune the rest.
        self._blocked_since = {
            k: v for k, v in self._blocked_since.items()
            if k in live_pods
            and live_pods[k].status.phase == PodPhase.PENDING}

    def _inject_workload_token(self, pod: Pod, env: dict[str, str]) -> bool:
        """GROVE_API_TOKEN = the pod's PCS workload identity token
        (satokensecret analog): in-pod engines authenticate metric
        pushes with a PCS-scoped credential instead of inheriting
        whatever operator token sits in the kubelet's environment. An
        explicit container-env value wins; inherited shell values are
        OVERRIDDEN — leaking the operator credential into workloads is
        the failure mode this exists to close.

        Returns False on a TRANSIENT read failure: env is fixed at
        exec, so launching credential-less would silently 401 every
        metric push for the pod's whole life — defer the launch and let
        the next tick retry instead. A genuinely absent secret (legacy
        PCS, conflict) launches without a token."""
        if "GROVE_API_TOKEN" in pod.spec.container.env:
            return True
        env.pop("GROVE_API_TOKEN", None)       # never leak operator creds
        pcs_name = pod.meta.labels.get(c.LABEL_PCS_NAME)
        if not pcs_name:
            return True
        from grove_tpu.api.core import Secret
        from grove_tpu.api.namegen import workload_token_secret_name
        from grove_tpu.runtime.errors import (
            ForbiddenError,
            GroveError,
            NotFoundError,
        )
        try:
            sec = self.client.get(Secret,
                                  workload_token_secret_name(pcs_name),
                                  pod.meta.namespace)
        except NotFoundError:
            return True
        except ForbiddenError:
            # Persistent: this agent's credential cannot read Secrets
            # (not a system actor) — deferring would deadlock the
            # launch. Run without workload identity and say why.
            self.log.warning("pod %s: agent credential may not read the "
                             "workload token secret; launching without "
                             "workload identity", pod.meta.name)
            return True
        except GroveError as e:
            self.log.warning("pod %s: workload token read failed (%s); "
                             "deferring launch", pod.meta.name, e)
            return False
        token = sec.data.get("token", "")
        if token:
            env["GROVE_API_TOKEN"] = token
        return True

    def _launch(self, pod: Pod, node: Node) -> None:
        argv = pod.spec.container.argv
        if not argv:
            self._set_exit_status(pod, 0)
            return
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update(pod.spec.container.env)
        if not self._inject_workload_token(pod, env):
            return                             # retried next tick
        env["GROVE_POD_NAME"] = pod.meta.name
        env["GROVE_NAMESPACE"] = pod.meta.namespace
        env["GROVE_NODE_NAME"] = node.meta.name
        env[c.ENV_TPU_SLICE_NAME] = node.meta.labels.get(c.NODE_LABEL_SLICE, "")
        env[c.ENV_TPU_SLICE_TOPOLOGY] = node.meta.labels.get(
            c.NODE_LABEL_TPU_TOPOLOGY, "")
        probe = pod.spec.container.readiness_file
        if probe:
            # A leftover file from a crashed prior incarnation would mark
            # the fresh process Ready while it is still starting up. Must
            # happen BEFORE exec: a fast-starting payload may write the
            # file immediately, and removing it afterwards would wedge the
            # pod NotReady forever.
            path = probe if os.path.isabs(probe) else os.path.join(
                pod.spec.container.workdir or self.workdir or ".", probe)
            try:
                os.remove(path)
            except OSError:
                pass
        try:
            os.makedirs(self.log_dir, exist_ok=True)
            log_path = os.path.join(
                self.log_dir,
                f"{pod.meta.namespace}.{pod.meta.name}.{pod.meta.uid[:8]}.log")
            with open(log_path, "ab") as log_file:
                proc = subprocess.Popen(
                    argv, env=env,
                    cwd=pod.spec.container.workdir or self.workdir or None,
                    stdout=log_file, stderr=subprocess.STDOUT,
                    start_new_session=True)
        except OSError as e:
            self.log.warning("pod %s: exec failed: %s", pod.meta.name, e)

            def exec_failed(p: Pod) -> None:
                p.status.phase = PodPhase.FAILED
                p.status.message = f"exec failed: {e}"

            self._write_status(pod, exec_failed)
            return
        self._procs[(pod.meta.namespace, pod.meta.name)] = \
            (pod.meta.uid, proc)

        def running(p: Pod) -> None:
            p.status.phase = PodPhase.RUNNING
            p.status.start_time = time.time()
            if probe:
                # Ready comes later, when the probe file appears.
                p.status.conditions = set_condition(
                    p.status.conditions,
                    Condition(type=c.COND_READY, status="False",
                              reason="AwaitingReadinessFile", message=probe))
            else:
                p.status.conditions = set_condition(
                    p.status.conditions,
                    Condition(type=c.COND_READY, status="True",
                              reason="ProcessRunning"))

        self._write_status(pod, running)
        self.log.info("pod %s: started pid %d on %s", pod.meta.name,
                      proc.pid, node.meta.name)

    def _probe_readiness(self, pod: Pod) -> bool:
        """Flip Ready → True once a declared readiness file appears,
        honoring the probe-timing contract (admission-validated bounds):
        no check before initial_delay after start; checks at most every
        period; a timeout > 0 FAILS the pod if the file never appears
        within initial_delay + timeout (→ MinAvailableBreached → the
        standard gang self-heal, exactly what a pod that will never
        serve should trigger). Returns True iff the pod was failed for
        probe timeout this call."""
        spec = pod.spec.container
        probe = spec.readiness_file
        if not probe:
            return False
        ready = next((cd for cd in pod.status.conditions
                      if cd.type == c.COND_READY), None)
        if ready is not None and ready.status == "True":
            return False
        now = time.time()
        started = pod.status.start_time or now
        if now < started + spec.readiness_initial_delay_s:
            return False
        key = (pod.meta.namespace, pod.meta.name)
        last = self._last_probe.get(key, 0.0)
        if now - last < spec.readiness_period_s:
            return False
        self._last_probe[key] = now
        path = probe if os.path.isabs(probe) else os.path.join(
            pod.spec.container.workdir or self.workdir or ".", probe)
        if not os.path.exists(path):
            t = spec.readiness_timeout_s
            if t > 0 and now > started + spec.readiness_initial_delay_s + t:
                self.log.warning("pod %s: readiness probe timed out "
                                 "(%.1fs); failing", pod.meta.name, t)
                entry = self._procs.pop(key, None)
                if entry is not None:
                    self._terminate(key, entry[1])

                def probe_timeout(p: Pod) -> None:
                    p.status.phase = PodPhase.FAILED
                    p.status.message = f"readiness probe timed out ({t}s)"
                    p.status.conditions = set_condition(
                        p.status.conditions,
                        Condition(type=c.COND_READY, status="False",
                                  reason="ProbeTimeout", message=probe))

                self._write_status(pod, probe_timeout)
                return True
            return False

        def mark_ready(p: Pod) -> None:
            p.status.conditions = set_condition(
                p.status.conditions,
                Condition(type=c.COND_READY, status="True",
                          reason="ReadinessFilePresent"))

        self._write_status(pod, mark_ready)
        self.log.info("pod %s: readiness file present", pod.meta.name)

    def _set_exit_status(self, pod: Pod, code: int) -> None:
        def exited(p: Pod) -> None:
            p.status.phase = (PodPhase.SUCCEEDED if code == 0
                              else PodPhase.FAILED)
            p.status.message = f"exit code {code}"
            p.status.conditions = set_condition(
                p.status.conditions,
                Condition(type=c.COND_READY, status="False",
                          reason="ProcessExited", message=f"code {code}"))
        self._write_status(pod, exited)

    def _write_status(self, pod: Pod, mutate) -> None:
        """Apply ``mutate`` to a fresh read and write, retrying conflicts —
        a swallowed conflict here would permanently lose an exit status
        (the proc entry is already reaped, so no later pass retries)."""
        for _ in range(5):
            try:
                live = self.client.get(Pod, pod.meta.name, pod.meta.namespace)
                if live.meta.uid != pod.meta.uid:
                    return  # replaced under the same name; not our pod
                mutate(live)
                self.client.update_status(live)
                return
            except NotFoundError:
                return
            except GroveError:
                time.sleep(0.01)
        self.log.warning("pod %s: status write kept conflicting; dropped",
                         pod.meta.name)

    def _terminate(self, key, proc: subprocess.Popen) -> None:
        self._procs.pop(key, None)
        self._last_probe.pop(key, None)
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGTERM)
                proc.wait(timeout=2.0)
            except (ProcessLookupError, subprocess.TimeoutExpired, PermissionError):
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                try:
                    proc.wait(timeout=1.0)  # reap — no zombies
                except subprocess.TimeoutExpired:
                    pass
        self.log.info("pod %s/%s: process terminated", *key)
