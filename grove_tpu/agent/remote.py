"""Remote node agent: one per TPU host, talking to the control plane
over HTTP.

The multi-host deployment shape: a single ``grovectl serve`` daemon owns
the store and controllers; every TPU host runs ``grovectl agent`` with
an ``HttpClient`` pinned to its node. The agent

1. self-registers its Node (labels = the GKE TPU node-label contract,
   built by ``topology.fleet.build_node``) if it does not exist, and
   publishes capacity via a status write (the wire create path cannot
   carry status, and allocatable_chips defaults to 0 — an unpublished
   node would never receive a pod),
2. heartbeats ``status.heartbeat_time``/``ready`` at a fixed cadence
   (the node-lease analog), and
3. runs a ``ProcessKubelet`` against the HttpClient — pods bound to the
   node exec as OS processes, with the startup barrier and status
   write-backs flowing over the wire exactly as they do in-process
   (ProcessKubelet is client-agnostic by construction).

Role parity: the reference's workload pods land on kubelet-run nodes and
its initc watches the apiserver from inside the pod boundary
(operator/initc/); here the host agent IS the kubelet analog and the
barrier runs in it, before exec.
"""

from __future__ import annotations

import threading
import time

from grove_tpu.agent.process import ProcessKubelet
from grove_tpu.api import Node
from grove_tpu.runtime.errors import GroveError, NotFoundError
from grove_tpu.runtime.logger import get_logger


class RemoteAgent:
    def __init__(self, client, node_name: str, register: Node | None = None,
                 namespace: str = "default", heartbeat_seconds: float = 5.0,
                 tick: float = 0.25, workdir: str | None = None,
                 log_dir: str | None = None,
                 extra_env: dict[str, str] | None = None,
                 use_watch: bool = True):
        """``client`` is any store-client surface (HttpClient in real
        deployments; an in-process Client works for tests). ``register``
        is the Node to create if absent — None means the node must
        already exist (pre-provisioned fleet).

        With ``use_watch`` and an HttpClient, the agent consumes the
        server's event feed and wakes the kubelet immediately on pod
        events — ``tick`` then only bounds the polling fallback, so it
        can be slow without costing reaction latency."""
        self.client = client
        self.node_name = node_name
        self.register = register
        self.namespace = namespace
        self.heartbeat_seconds = heartbeat_seconds
        self.log = get_logger("agent.remote")
        self._wake = threading.Event()
        self._use_watch = use_watch and hasattr(client, "watch_events")
        self.kubelet = ProcessKubelet(
            client, namespace=namespace, node_name=node_name,
            tick=(max(tick, 2.0) if self._use_watch else tick),
            workdir=workdir, log_dir=log_dir, extra_env=extra_env,
            wake=self._wake)
        self._stop = threading.Event()
        self._hb_thread: threading.Thread | None = None
        self._watch_thread: threading.Thread | None = None

    def start(self) -> None:
        self.ensure_node()
        self.kubelet.start()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           name="agent-heartbeat",
                                           daemon=True)
        self._hb_thread.start()
        if self._use_watch:
            self._watch_thread = threading.Thread(
                target=self._watch_loop, name="agent-watch", daemon=True)
            self._watch_thread.start()
        self.log.info("remote agent up: node %s (watch=%s)",
                      self.node_name, self._use_watch)

    def stop(self) -> None:
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(2.0)
        self.kubelet.stop()
        # The watch thread is daemon + blocks in a long poll; it dies
        # with the process (the server also unblocks it at timeout).

    def _watch_loop(self) -> None:
        """Consume the wire event feed; any Pod/PodClique event wakes the
        kubelet (it re-lists, so coarse filtering is enough). Gaps and
        transport errors are absorbed by the shared relist-and-resume
        helper — a history-ring gap forces a prompt re-list pass (the
        kubelet IS this consumer's cache) instead of crashing the
        agent; the fallback tick covers any blind window."""
        from grove_tpu.store.httpclient import resumable_watch_events
        for _seq, _etype, _obj in resumable_watch_events(
                self.client, kinds=["Pod", "PodClique"], namespace=None,
                poll_timeout=20.0, stop=self._stop,
                on_gap=self._wake.set,
                on_error=lambda e: self.log.warning(
                    "watch feed error: %s; retrying", e)):
            self._wake.set()
            if self._stop.is_set():
                return

    def ensure_node(self) -> None:
        try:
            self.client.get(Node, self.node_name, self.namespace)
            return
        except NotFoundError:
            pass
        if self.register is None:
            raise GroveError(
                f"node {self.node_name!r} not found and no registration "
                "template given (pass --register)")
        assert self.register.meta.name == self.node_name, \
            (self.register.meta.name, self.node_name)
        self.client.create(self.register)
        self.log.info("registered node %s (%d chips)", self.node_name,
                      self.register.spec.tpu_chips)

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            self.heartbeat()
            self._stop.wait(self.heartbeat_seconds)

    def heartbeat(self) -> None:
        """Publish ready/capacity/heartbeat_time (read-modify-write with
        conflict retry; a missed beat is retried next period)."""
        for _ in range(3):
            try:
                node = self.client.get(Node, self.node_name, self.namespace)
                node.status.ready = True
                if node.status.allocatable_chips == 0:
                    node.status.allocatable_chips = node.spec.tpu_chips
                node.status.heartbeat_time = time.time()
                self.client.update_status(node)
                return
            except NotFoundError:
                return  # deregistered underneath us; next beat re-checks
            except GroveError as e:
                last = e
                time.sleep(0.05)
        self.log.warning("heartbeat failed: %s", last)
