from grove_tpu.agent.node import FakeKubeletPool
from grove_tpu.agent.barrier import barrier_satisfied

__all__ = ["FakeKubeletPool", "barrier_satisfied"]
