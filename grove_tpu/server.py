"""HTTP API server — the apiserver-facing surface of the control plane.

The reference is driven through kube-apiserver; grove-tpu's standalone
control plane exposes its own minimal HTTP API so out-of-process clients
(dashboards, CI, other hosts' agents) can operate it:

  GET  /healthz                       manager health (JSON)
  GET  /metrics                       Prometheus text
  GET  /api/<kind>                    list (JSON; ?namespace=, label
                                      selectors via ?l.<key>=<value>,
                                      status-field selectors via
                                      ?f.<field>=<v1,v2> — server-side
                                      filtering BEFORE serialization, the
                                      kube fieldSelector analog: an agent
                                      fleet asking for its own nodes'
                                      Pending pods must not make the
                                      server serialize the whole fleet's
                                      pod list per poll)
  GET  /api/<kind>/<name>             get one
  GET  /logs/<ns>/<pod>               pod logs (?tail=N; kubectl-logs analog)
  GET  /watch                         resumable long-poll event feed
                                      (?since=<rv>&timeout=&kinds=A,B&
                                      namespace=&l.<k>=<v>); since past
                                      the history ring -> 410 Gone,
                                      relist and resume (kube watch
                                      semantics). The informer feed for
                                      remote agents.
  GET  /debug/profile                 all-threads sampling profile over a
                                      window (?seconds=, ?format=collapsed|
                                      top); pprof-endpoint analog, gated by
                                      config.profiling.enabled
  GET  /debug/stacks                  all-threads stack dump (goroutine
                                      dump analog; same gate)
  GET  /debug/traces                  gang-lifecycle flight recorder:
                                      raw spans + milestones
                                      (?trace_id= filters one trace;
                                      grovectl trace renders it; same
                                      gate)
  GET  /debug/placement/<ns>/<name>   raw placement diagnosis for one
                                      PodGang (status.last_diagnosis +
                                      conditions; grovectl explain
                                      renders it; plain status data, so
                                      read-gated, not profiling-gated)
  GET  /debug/deploy/<ns>/<name>      deploy-progress record for one
                                      PodCliqueSet (pods per stage,
                                      milestones, write amplification,
                                      queue-wait vs work split; grovectl
                                      deploy-status renders it; same
                                      read gate as /debug/placement)
  GET  /debug/serving/<ns>/<name>     serving SLO state for one scaling
                                      scope (TTFT/TPOT percentiles vs
                                      target, queue depth, KV headroom,
                                      reporter liveness; grovectl
                                      serving-status renders it; same
                                      read gate as /debug/placement)
  GET  /debug/xprof/<ns>/<name>       data-plane observatory payload
                                      for one serving engine (compile
                                      table, device-time phase
                                      breakdown, memory accounting,
                                      roofline estimates; grovectl
                                      engine-profile renders it; same
                                      read gate as /debug/placement)
  GET  /debug/requests/<ns>/<name>    request observatory payload for
                                      one serving engine (per-request
                                      span traces, p99 phase
                                      attribution, slowest-K ring;
                                      grovectl request-trace renders
                                      it; same read gate as
                                      /debug/xprof)
  GET  /debug/disruption              disruption-contract ledger: live
                                      notices with barrier state,
                                      in-flight/recent spot-reclaim
                                      evacuations (grovectl
                                      disruptions renders it)
  GET  /debug/defrag                  defrag plan ledger: in-flight
                                      migration, recent plans, budget
                                      (grovectl defrag-status renders
                                      it; same read gate as
                                      /debug/placement)
  GET  /debug/controlplane            control-plane observatory: per-
                                      controller sweep attribution,
                                      write-amplification ledger,
                                      watch-lag SLO (grovectl
                                      controlplane-status renders it;
                                      same read gate as /debug/defrag)
  GET  /debug/leadership              this replica's leadership view:
                                      role, fencing epoch, transitions,
                                      leader hint (grovectl
                                      leader-status renders it; same
                                      read gate as /debug/placement).
                                      Mutating verbs on a non-leader
                                      replica return 503 + the hint;
                                      an X-Grove-Epoch request header
                                      stamps the write with the
                                      caller's claimed fencing epoch
                                      (stale epoch -> 409)
  POST /apply                         YAML/JSON manifest (create-or-
                                      update; ?dry_run=1 = admission-only
                                      server-side dry run)
  PATCH /api/<kind>/<name>            RFC 7386 JSON merge patch on
                                      spec/labels/annotations
  PUT  /api/<kind>/<name>/status      status-subresource write (full
                                      object body; optimistic concurrency
                                      — stale resource_version is 409).
                                      The remote node agent's write path.
  POST /metrics/push                  workload autoscaling signals
  DELETE /api/<kind>/<name>           delete

Authentication: mutating verbs require `Authorization: Bearer <token>`,
mapped to an actor identity by ServerAuthConfig.tokens; anonymous
mutations are rejected (401) and the mapped actor is impersonated on the
store client so admission authorization fires on the wire path exactly
as it does in-process — a token mapped to a plain user cannot mutate
grove-managed children (403). Reads and /metrics/push stay open by
default (config-gated). TLS: config.server_tls enables managed
certificates (self-provisioned CA + rotated leaf, or BYO files — the
reference's webhook cert machinery, cert.go:50-117; see
grove_tpu/runtime/certs.py); clients pin the CA via HttpClient(ca_file=)
or ``grovectl --ca``. Default remains plain loopback TCP.

Single-threaded-per-request stdlib server (ThreadingHTTPServer): the
store is already thread-safe, and control-plane traffic is low-rate.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from grove_tpu.api import constants as c
from grove_tpu.api.serde import from_dict, to_dict
from grove_tpu.manifest import KIND_REGISTRY, load_manifest, load_object
from grove_tpu.runtime.errors import (
    ConflictError,
    ForbiddenError,
    GroveError,
    NotFoundError,
)

ANONYMOUS_ACTOR = "system:anonymous"


class ApiServer:
    def __init__(self, cluster, host: str = "127.0.0.1", port: int = 8087):
        self.cluster = cluster
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None
        self._certs = None              # CertManager when TLS is on
        self._rotate_timer: threading.Timer | None = None
        self._stopped = False
        self._token_index: dict[str, str] = {}
        self._token_index_at = 0.0
        self._token_lock = threading.Lock()

    TOKEN_INDEX_TTL = 2.0

    def _workload_token_index(self) -> dict[str, str]:
        """sha256(token) -> workload actor, rebuilt at most every TTL
        seconds. Hash-keyed so lookup is one digest + one dict hit
        (timing-safe: the comparison happens on digests) instead of an
        O(secrets) scan on the metrics hot path. A freshly minted token
        may be unknown for up to one TTL; metric pushers retry, and
        that beats a cluster-wide Secret list per request."""
        import hashlib
        import time as _time

        now = _time.monotonic()
        with self._token_lock:
            if now - self._token_index_at < self.TOKEN_INDEX_TTL:
                return self._token_index
            from grove_tpu.api import constants as _c
            from grove_tpu.api.core import Secret

            index: dict[str, str] = {}
            for s in self.cluster.client.list(
                    Secret, None,
                    selector={_c.LABEL_TOKEN_KIND: _c.TOKEN_KIND_WORKLOAD,
                              _c.LABEL_MANAGED_BY:
                                  _c.LABEL_MANAGED_BY_VALUE}):
                pcs = s.meta.labels.get(_c.LABEL_PCS_NAME, "")
                token = s.data.get("token", "")
                if pcs and token:
                    digest = hashlib.sha256(token.encode()).hexdigest()
                    index[digest] = (f"{_c.WORKLOAD_ACTOR_PREFIX}"
                                     f"{s.meta.namespace}:{pcs}")
            self._token_index = index
            self._token_index_at = now
            return index

    @property
    def scheme(self) -> str:
        return "https" if self._certs is not None else "http"

    @property
    def ca_file(self) -> str | None:
        """Trust anchor clients should pin (self-managed mode), the
        configured ca_file (byo), or None over plain HTTP."""
        if self._certs is None:
            return None
        paths = self._certs.ensure()
        return paths.ca_file or None

    def _setup_tls(self) -> None:
        """Wrap the listening socket when config.server_tls.enabled —
        the C6 cert-controller analog (self-managed CA + rotated leaf,
        or BYO files; grove_tpu/runtime/certs.py)."""
        tls = self.cluster.manager.config.server_tls
        if not tls.enabled:
            return
        from grove_tpu.runtime.certs import CertManager

        self._certs = CertManager(tls)
        ctx = self._certs.server_context()
        # Handshake is deferred to the per-connection handler thread
        # (Handler.setup): with do_handshake_on_connect=True the accept
        # loop itself would run the handshake, so one client that opens
        # a TCP connection and never speaks TLS wedges ALL accepts.
        self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                             server_side=True,
                                             do_handshake_on_connect=False)
        if tls.mode != "byo" and tls.rotation_check_seconds > 0:
            self._schedule_rotation(tls.rotation_check_seconds)

    def _schedule_rotation(self, period: float) -> None:
        def tick():
            if self._stopped:
                return
            try:
                self._certs.maybe_rotate()
            except Exception:           # noqa: BLE001 — keep serving on
                pass                    # the old leaf; next tick retries
            # Re-check after the (possibly slow) rotation: a stop() that
            # raced this tick must not leave a fresh timer pinning the
            # dead server for another period.
            if not self._stopped:
                self._schedule_rotation(period)

        self._rotate_timer = threading.Timer(period, tick)
        self._rotate_timer.daemon = True
        self._rotate_timer.start()

    def start(self) -> None:
        cluster = self.cluster
        api = self

        # Watch-replay render cache: an event's object is serialized
        # once per (uid, rv) STATE, not once per watcher per poll — with
        # M agents watching a deploy storm, re-walking every dataclass
        # for every watcher made replay the server's dominant cost
        # (measured: ~5s of a 300-pod create phase).
        import collections as _collections

        render_cache: "_collections.OrderedDict[tuple, dict]" = \
            _collections.OrderedDict()
        render_lock = threading.Lock()

        def render_event_obj(obj) -> str:
            """Serialized JSON of the object — cached so both the
            dataclass walk AND json.dumps happen once per state, not
            once per watcher per poll."""
            key = (obj.KIND, obj.meta.uid, obj.meta.resource_version)
            with render_lock:
                hit = render_cache.get(key)
                if hit is not None:
                    render_cache.move_to_end(key)
                    return hit
            data = json.dumps(to_dict(obj))
            with render_lock:
                render_cache[key] = data
                if len(render_cache) > 4096:   # ≥ the event-history ring
                    render_cache.popitem(last=False)
            return data

        class Handler(BaseHTTPRequestHandler):
            def setup(self):
                # TLS handshake runs HERE, in this connection's own
                # thread with a bounded timeout (see _setup_tls for why
                # not in the accept loop). Cleared afterwards so the
                # timeout never fires inside a long-poll /watch.
                if api._certs is not None:
                    self.request.settimeout(10.0)
                    self.request.do_handshake()
                    self.request.settimeout(None)
                super().setup()

            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, payload,
                      content_type="application/json",
                      preserialized: bool = False):
                body = (payload.encode() if preserialized
                        else json.dumps(payload, indent=2).encode()
                        if content_type == "application/json"
                        else payload.encode())
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _kind(self, token: str):
                cls = KIND_REGISTRY.get(token)
                if cls is None:
                    self._send(404, {"error": f"unknown kind {token!r}",
                                     "kinds": sorted(KIND_REGISTRY)})
                return cls

            def _guard_secret_access(self, cls) -> bool:
                """Secrets hold credentials: EVERY wire verb that can
                touch or echo one requires a SYSTEM actor — reads,
                and also mutations, whose responses echo the object
                (admission catches mutations too, but only when the
                authorizer is enabled; this guard holds even in the
                dev escape-hatch config). Returns False after sending
                the error."""
                if cls.KIND != "Secret" or self._secret_visible():
                    return True
                self._send(403, {"error": "Secret access requires a "
                                 "system-actor bearer token"})
                return False

            def _auth_config(self):
                return cluster.manager.config.server_auth

            def _actor(self) -> str | None:
                """Actor for this request: a token-mapped identity,
                ANONYMOUS_ACTOR without credentials, or None (invalid
                token — the caller tried to authenticate and failed)."""
                hdr = self.headers.get("Authorization", "")
                if not hdr:
                    return ANONYMOUS_ACTOR
                if not hdr.startswith("Bearer "):
                    return None
                token = hdr[7:].strip()
                actor = self._auth_config().tokens.get(token)
                if actor is not None:
                    return actor
                return self._workload_actor(token)

            def _workload_actor(self, token: str) -> str | None:
                """Resolve a control-plane-minted workload token (the
                per-PCS Secret, satokensecret analog) to its PCS-scoped
                actor, via the server's TTL-cached index — the steady-
                state metrics hot path (and garbage-token floods) must
                not list Secrets per request. The identity derives from
                the secret's OWN labels — data never names an actor, so
                a user-minted secret cannot escalate (and unmanaged
                secrets are ignored outright)."""
                import hashlib

                digest = hashlib.sha256(token.encode()).hexdigest()
                return api._workload_token_index().get(digest)

            def _secret_visible(self) -> bool:
                """ONE rule for every wire surface that can show Secret
                material (reads, watch events): system actors only."""
                from grove_tpu.admission.authorization import (
                    _SYSTEM_ACTORS,
                )
                actor = self._actor()
                return (actor in _SYSTEM_ACTORS
                        or (actor or "") in cluster.manager.config
                        .authorizer.exempt_actors)

            def _mutating_client(self):
                """Impersonated client for a mutating request, or None
                after an error response has been sent. A non-leader
                replica refuses every mutation with 503 + a leader
                hint (clients follow it — HttpClient / cli._http);
                an X-Grove-Epoch header stamps the returned client so
                the store's fence judges the caller's claimed term."""
                leadership = cluster.manager.leadership
                if not leadership.is_leader:
                    self._send(503, {
                        "error": "this replica is not the leader; "
                                 "writes must go to the leader",
                        "leader": leadership.payload().get(
                            "leader_hint", "")})
                    return None
                actor = self._actor()
                if actor is None:
                    self._send(401, {"error": "invalid bearer token"})
                    return None
                if actor.startswith(c.WORKLOAD_ACTOR_PREFIX):
                    # Metrics-only credential: a pod's token must grant
                    # strictly LESS than anonymity does, not more.
                    self._send(403, {"error":
                                     "workload tokens only authenticate "
                                     "metric pushes; mutations need an "
                                     "operator/user token"})
                    return None
                if actor == ANONYMOUS_ACTOR and \
                        not self._auth_config().allow_anonymous_mutations:
                    self._send(401, {"error":
                                     "authentication required: mutating "
                                     "verbs need Authorization: Bearer "
                                     "<token> (see server_auth.tokens)"})
                    return None
                client = cluster.client.impersonate(actor)
                epoch_hdr = self.headers.get("X-Grove-Epoch", "")
                if epoch_hdr:
                    # The wire writer claims a fencing epoch: stamp the
                    # per-request client so the store's fence applies to
                    # this write exactly as to an in-process one. A bad
                    # header is a bad request, not an unfenced write.
                    try:
                        client.epoch = int(epoch_hdr)
                    except ValueError:
                        self._send(400, {"error": f"bad X-Grove-Epoch "
                                         f"{epoch_hdr!r}; must be an "
                                         "integer"})
                        return None
                return client

            def do_GET(self):
                url = urlparse(self.path)
                parts = [p for p in url.path.split("/") if p]
                try:
                    # healthz/metrics are always open: liveness probes
                    # must not need credentials.
                    if url.path == "/healthz":
                        self._send(200, cluster.manager.healthz())
                        return
                    if url.path == "/metrics":
                        self._send(200, cluster.manager.metrics_text(),
                                   content_type="text/plain; version=0.0.4")
                        return
                    if self._auth_config().require_token_for_reads:
                        actor = self._actor()
                        if actor is None or actor == ANONYMOUS_ACTOR:
                            self._send(401, {"error": "reads require a "
                                             "bearer token"})
                            return
                    if len(parts) == 2 and parts[0] == "api":
                        cls = self._kind(parts[1])
                        if cls is None:
                            return
                        if not self._guard_secret_access(cls):
                            return
                        q = parse_qs(url.query)
                        # "*" = all namespaces (kubectl -A analog).
                        ns = q.get("namespace", ["default"])[0]
                        selector = {k[2:]: v[0] for k, v in q.items()
                                    if k.startswith("l.")}
                        fields = {k[2:]: v[0] for k, v in q.items()
                                  if k.startswith("f.")}
                        # Unknown status-field names fail LOUDLY (400,
                        # kube's "field selector not supported" analog):
                        # matches_fields treats a missing attr as '', so
                        # a typo'd key would otherwise silently match
                        # nothing and an agent would quietly stop seeing
                        # all its pods.
                        import dataclasses as _dc
                        st = getattr(cls(), "status", None) \
                            if fields else None
                        known = ({f.name for f in _dc.fields(type(st))}
                                 if _dc.is_dataclass(st) else set())
                        bad = sorted(set(fields) - known)
                        if bad:
                            self._send(400, {"error":
                                f"unsupported status field selector(s) "
                                f"{', '.join(bad)} for {cls.KIND}; "
                                f"known: {', '.join(sorted(known))}"})
                            return
                        objs = cluster.client.list(
                            cls, None if ns == "*" else ns,
                            selector or None, fields=fields or None)
                        self._send(200, [to_dict(o) for o in objs])
                    elif len(parts) == 3 and parts[0] == "api":
                        cls = self._kind(parts[1])
                        if cls is None:
                            return
                        if not self._guard_secret_access(cls):
                            return
                        q = parse_qs(url.query)
                        ns = q.get("namespace", ["default"])[0]
                        self._send(200, to_dict(
                            cluster.client.get(cls, parts[2], ns)))
                    elif len(parts) == 3 and parts[0] == "logs":
                        self._pod_logs(parts[1], parts[2],
                                       parse_qs(url.query))
                    elif url.path == "/watch":
                        self._watch(parse_qs(url.query))
                    elif url.path == "/debug/profile":
                        self._debug_profile(parse_qs(url.query))
                    elif url.path == "/debug/stacks":
                        self._debug_stacks()
                    elif url.path == "/debug/traces":
                        self._debug_traces(parse_qs(url.query))
                    elif len(parts) == 4 and parts[0] == "debug" \
                            and parts[1] == "placement":
                        self._debug_placement(parts[2], parts[3])
                    elif len(parts) == 4 and parts[0] == "debug" \
                            and parts[1] == "deploy":
                        self._debug_deploy(parts[2], parts[3])
                    elif len(parts) == 4 and parts[0] == "debug" \
                            and parts[1] == "serving":
                        self._debug_serving(parts[2], parts[3])
                    elif len(parts) == 4 and parts[0] == "debug" \
                            and parts[1] == "xprof":
                        self._debug_xprof(parts[2], parts[3])
                    elif len(parts) == 4 and parts[0] == "debug" \
                            and parts[1] == "requests":
                        self._debug_requests(parts[2], parts[3])
                    elif url.path == "/debug/defrag":
                        self._debug_defrag()
                    elif url.path == "/debug/disruption":
                        self._debug_disruption()
                    elif url.path == "/debug/leadership":
                        self._debug_leadership()
                    elif url.path == "/debug/controlplane":
                        self._debug_controlplane()
                    else:
                        self._send(404, {"error": "not found"})
                except NotFoundError as e:
                    self._send(404, {"error": str(e)})
                except GroveError as e:
                    self._send(400, {"error": str(e)})

            def do_POST(self):
                path = urlparse(self.path).path
                if path == "/metrics/push":
                    self._metrics_push()
                    return
                parts = [p for p in path.split("/") if p]
                if len(parts) == 3 and parts[0] == "batch" \
                        and parts[2] == "status":
                    self._status_batch(parts[1])
                    return
                if path != "/apply":
                    self._send(404, {"error": "POST /apply, /metrics/push "
                                     "or /batch/<kind>/status"})
                    return
                client = self._mutating_client()
                if client is None:
                    return
                # ?dry_run=1: run the FULL admission chain (defaulting,
                # validation, authorization) per object and report the
                # would-be actions, committing nothing — the kubectl
                # apply --dry-run=server analog.
                dry_run = parse_qs(urlparse(self.path).query).get(
                    "dry_run", ["0"])[0].lower() in ("1", "true", "yes")
                length = int(self.headers.get("Content-Length", "0"))
                raw = self.rfile.read(length).decode()
                try:
                    if self.headers.get("Content-Type", "").startswith(
                            "application/json"):
                        objs = [load_object(json.loads(raw))]
                    else:
                        objs = load_manifest(raw)
                    for obj in objs:
                        if not self._guard_secret_access(type(obj)):
                            return
                    if dry_run:
                        self._send(200, self._apply_dry_run(client, objs))
                        return
                    results = []
                    forbidden = False
                    for obj in objs:
                        try:
                            created = client.create(obj)
                            results.append({"kind": created.KIND,
                                            "name": created.meta.name,
                                            "action": "created"})
                        except ForbiddenError as e:
                            # Report per-object and keep going: earlier
                            # documents were already applied, and hiding
                            # that behind an opaque 403 would leave the
                            # caller blind to what now exists.
                            forbidden = True
                            results.append({"kind": obj.KIND,
                                            "name": obj.meta.name,
                                            "action": "forbidden",
                                            "error": str(e)})
                        except GroveError as e:
                            if "exists" not in str(e):
                                raise
                            try:
                                live = client.get(type(obj), obj.meta.name,
                                                  obj.meta.namespace)
                                live.spec = obj.spec
                                client.update(live)
                                results.append({"kind": obj.KIND,
                                                "name": obj.meta.name,
                                                "action": "updated"})
                            except ForbiddenError as e2:
                                forbidden = True
                                results.append({"kind": obj.KIND,
                                                "name": obj.meta.name,
                                                "action": "forbidden",
                                                "error": str(e2)})
                    self._send(403 if forbidden else 200, results)
                except ConflictError as e:
                    # Fenced or rv-stale apply: 409 so wire clients see
                    # the same conflict taxonomy as PUT/PATCH.
                    self._send(409, {"error": str(e)})
                except GroveError as e:
                    self._send(400, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 - malformed input
                    self._send(400, {"error": f"bad manifest: {e}"})

            def _apply_dry_run(self, client, objs) -> list:
                """Admission-only pass over a manifest: each object is
                defaulted + validated + authorized against live state
                via the store's own admission dispatch (ONE path shared
                with real writes), committing nothing."""
                results = []
                for obj in objs:
                    try:
                        action = client.dry_run_admit(obj)
                        results.append({"kind": obj.KIND,
                                        "name": obj.meta.name,
                                        "action": action})
                    except ForbiddenError as e:
                        results.append({"kind": obj.KIND,
                                        "name": obj.meta.name,
                                        "action": "forbidden",
                                        "error": str(e)})
                    except GroveError as e:
                        results.append({"kind": obj.KIND,
                                        "name": obj.meta.name,
                                        "action": "invalid",
                                        "error": str(e)})
                return results

            def _pod_logs(self, namespace: str, pod: str, q):
                """GET /logs/<namespace>/<pod>[?tail=N] — kubectl-logs
                analog, served from the process kubelets' log dirs
                (newest incarnation)."""
                import glob
                import os
                from grove_tpu.agent.process import ProcessKubelet
                tail = q.get("tail", [None])[0]
                if tail is not None:
                    try:
                        tail_n = int(tail)
                    except ValueError:
                        self._send(400, {"error": f"bad tail={tail!r}; "
                                         "must be an integer"})
                        return
                # glob.escape: the URL segments are literals, never
                # patterns (un-escaped, /logs/*/* would disclose any
                # pod's logs across namespaces).
                pattern = f"{glob.escape(namespace)}.{glob.escape(pod)}.*.log"
                candidates = []
                for r in cluster.manager.runnables:
                    if isinstance(r, ProcessKubelet):
                        candidates.extend(glob.glob(
                            os.path.join(glob.escape(r.log_dir), pattern)))
                if not candidates:
                    self._send(404, {"error": f"no logs for pod {pod!r} "
                                     "(fake nodes produce none)"})
                    return
                newest = max(candidates, key=os.path.getmtime)
                with open(newest, "rb") as f:
                    data = f.read().decode(errors="replace")
                if tail is not None:
                    lines = data.splitlines()[-tail_n:] if tail_n > 0 else []
                    data = "\n".join(lines) + ("\n" if lines else "")
                self._send(200, data, content_type="text/plain")

            def _watch(self, q):
                """Long-poll the store's event history. Returns
                {"rv": N, "events": [...]} — empty events on timeout
                (client re-polls with the same since); 410 when history
                no longer covers ``since``."""
                import time as _time

                store = cluster.manager.store
                try:
                    since = int(q.get("since", ["-1"])[0])
                    timeout = min(float(q.get("timeout", ["25"])[0]), 60.0)
                except ValueError:
                    self._send(400, {"error": "bad since/timeout value"})
                    return
                if since < 0:  # bootstrap: current rv, no events
                    self._send(200, {"rv": store.current_rv(),
                                     "events": []})
                    return
                kinds_arg = q.get("kinds", [""])[0]
                kinds = set(kinds_arg.split(",")) if kinds_arg else None
                ns = q.get("namespace", [None])[0]
                ns = None if ns in (None, "*") else ns
                selector = {k[2:]: v[0] for k, v in q.items()
                            if k.startswith("l.")} or None
                # Secret events carry credentials: visible only to
                # system actors (same rule as direct reads).
                secrets_ok = self._secret_visible()
                deadline = _time.time() + timeout
                while True:
                    events, ok, scanned = store.replay(since, kinds=kinds,
                                                       namespace=ns,
                                                       selector=selector)
                    if not secrets_ok:
                        events = [(seq, ev) for seq, ev in events
                                  if ev.obj.KIND != "Secret"]
                    if not ok:
                        self._send(410, {"error": f"history gone before "
                                         f"rv {since}; relist"})
                        return
                    # Advance past filtered-out events too: a cursor
                    # pinned at the last *matching* seq would 410 as
                    # soon as unrelated churn wraps the ring.
                    since = scanned
                    if events or _time.time() >= deadline:
                        # ts: emission wall time (Event.ts) — lets wire
                        # consumers compute event lag the same way the
                        # local informers do.
                        frags = (
                            f'{{"seq": {seq}, "type": "{ev.type.value}", '
                            f'"kind": "{ev.obj.KIND}", "ts": {ev.ts!r}, '
                            f'"object": {render_event_obj(ev.obj)}}}'
                            for seq, ev in events)
                        raw = (f'{{"rv": {since}, "events": '
                               f'[{",".join(frags)}]}}')
                        self._send(200, raw, content_type="application/json",
                                   preserialized=True)
                        return
                    store.wait_events(since,
                                      timeout=deadline - _time.time())
                    # Debounce: during a deploy storm events arrive one
                    # at a time; answering each wake immediately turns N
                    # events into N×watchers HTTP cycles (measured ~860
                    # req/s at 300 pods / 4 agents). 30ms of batching
                    # collapses the burst into one reply per watcher at
                    # a latency cost no reconcile loop can notice.
                    if _time.time() < deadline:
                        _time.sleep(min(0.03, max(0.0,
                                                  deadline - _time.time())))

            def _profiling_config(self):
                """Profiling config when the surface is enabled, else None
                (404 sent — the reference's pprof endpoints simply don't
                exist unless config enables them, manager.go:115-123)."""
                prof = cluster.manager.config.profiling
                if not prof.enabled:
                    self._send(404, {"error": "profiling disabled "
                                     "(config: profiling.enabled)"})
                    return None
                return prof

            def _debug_profile(self, q):
                """GET /debug/profile?seconds=N&format=collapsed|top —
                sample every thread's stack over the window."""
                from grove_tpu.runtime.profiler import profile_window
                prof = self._profiling_config()
                if prof is None:
                    return
                try:
                    seconds = float(q.get("seconds", ["1.0"])[0])
                except ValueError:
                    self._send(400, {"error": "bad seconds= value"})
                    return
                if not 0 < seconds <= prof.max_window_seconds:
                    self._send(400, {"error": f"seconds must be in "
                                     f"(0, {prof.max_window_seconds}]"})
                    return
                fmt = q.get("format", ["collapsed"])[0]
                if fmt not in ("collapsed", "top"):
                    self._send(400, {"error": "format must be "
                                     "collapsed|top"})
                    return
                sampler = profile_window(
                    seconds, interval=prof.sample_interval_ms / 1000.0)
                if fmt == "top":
                    self._send(200, {"seconds": seconds,
                                     "samples": sampler.samples,
                                     "top": sampler.top(30)})
                else:
                    self._send(200, sampler.collapsed(),
                               content_type="text/plain")

            def _debug_stacks(self):
                from grove_tpu.runtime.profiler import dump_stacks
                if self._profiling_config() is None:
                    return
                self._send(200, dump_stacks(), content_type="text/plain")

            def _debug_traces(self, q):
                """GET /debug/traces[?trace_id=] — the lifecycle
                flight recorder's raw spans + milestones (grovectl
                trace renders them). Same gate as /debug/profile:
                traces expose object names and timings."""
                if self._profiling_config() is None:
                    return
                tid = q.get("trace_id", [None])[0]
                self._send(200, cluster.manager.tracer.export(tid))

            def _debug_placement(self, namespace: str, name: str):
                """GET /debug/placement/<ns>/<name> — the raw placement
                diagnosis for one PodGang (``grovectl explain`` renders
                it). Plain status data (the same block a GET of the
                gang returns), so it shares the read gate, not the
                profiling gate."""
                from grove_tpu.api import PodGang
                from grove_tpu.scheduler.explain import placement_payload
                gang = cluster.client.get(PodGang, name, namespace)
                self._send(200, placement_payload(gang))

            def _debug_deploy(self, namespace: str, name: str):
                """GET /debug/deploy/<ns>/<name> — one PodCliqueSet's
                deploy-progress record (``grovectl deploy-status``
                renders it). Aggregate progress/consumption data, so it
                shares the read gate like /debug/placement, not the
                profiling gate. NotFoundError from the twin maps to 404
                in do_GET's handler."""
                self._send(200, cluster.client.debug_deploy(
                    name, namespace))

            def _debug_defrag(self):
                """GET /debug/defrag — the defrag controller's plan
                ledger (``grovectl defrag-status`` renders it).
                Aggregate placement-repair state like /debug/deploy, so
                it shares the read gate, not the profiling gate.
                NotFoundError from the twin maps to 404 in do_GET's
                handler."""
                self._send(200, cluster.client.debug_defrag())

            def _debug_disruption(self):
                """GET /debug/disruption — the disruption-contract
                ledger (``grovectl disruptions`` renders it): live
                notices with barrier state, in-flight and recent
                spot-reclaim evacuations, counters. Aggregate
                operational state like /debug/defrag, so it shares the
                read gate, not the profiling gate. NotFoundError from
                the twin maps to 404 in do_GET's handler."""
                self._send(200, cluster.client.debug_disruption())

            def _debug_leadership(self):
                """GET /debug/leadership — this replica's leadership
                view (``grovectl leader-status`` renders it): role,
                fencing epoch (claimed and the store's), transitions,
                leader hint. Plain operational state, so it shares the
                read gate like /debug/placement, not the profiling
                gate."""
                self._send(200, cluster.manager.leadership.payload(
                    cluster.manager.store))

            def _debug_controlplane(self):
                """GET /debug/controlplane — the control-plane
                observatory's sweep ledger (``grovectl
                controlplane-status`` renders it): per-controller
                reconcile attribution, write-amplification,
                hot-object top-K, watch-lag SLO. Aggregate operational
                state like /debug/defrag, so it shares the read gate,
                not the profiling gate. NotFoundError from the twin
                maps to 404 in do_GET's handler."""
                self._send(200, cluster.client.debug_controlplane())

            def _debug_serving(self, namespace: str, name: str):
                """GET /debug/serving/<ns>/<name> — one serving scope's
                SLO state (``grovectl serving-status`` renders it).
                Aggregate latency/load data like /debug/deploy, so it
                shares the read gate, not the profiling gate.
                NotFoundError from the twin maps to 404 in do_GET's
                handler."""
                self._send(200, cluster.client.debug_serving(
                    name, namespace))

            def _debug_xprof(self, namespace: str, name: str):
                """GET /debug/xprof/<ns>/<name> — one engine's
                data-plane observatory payload (``grovectl
                engine-profile`` renders it). Aggregate device-time/
                compile/memory data like /debug/serving, so it shares
                the read gate, not the profiling gate. NotFoundError
                from the twin maps to 404 in do_GET's handler."""
                self._send(200, cluster.client.debug_xprof(
                    name, namespace))

            def _debug_requests(self, namespace: str, name: str):
                """GET /debug/requests/<ns>/<name> — one engine's
                request-observatory payload (``grovectl
                request-trace`` renders it). Per-request spans and
                phase attribution, read-gated exactly like
                /debug/xprof. NotFoundError from the twin maps to 404
                in do_GET's handler."""
                self._send(200, cluster.client.debug_requests(
                    name, namespace))

            def _workload_owns(self, actor: str, payload: dict) -> bool:
                """A workload actor (system:workload:<ns>:<pcs>) may only
                report scaling signals for objects its own PCS owns —
                checked against the live object's PCS label, not a name
                prefix (PCS 'foo' must not reach 'foo-bar' objects)."""
                try:
                    _, _, ns, pcs = actor.split(":", 3)
                    kind = payload["kind"]
                    name = payload["name"]
                    target_ns = payload.get("namespace", "default")
                except (ValueError, KeyError, TypeError):
                    return False
                if target_ns != ns:
                    return False
                cls = KIND_REGISTRY.get(kind)
                if cls is None:
                    return False
                try:
                    obj = cluster.client.get(cls, name, target_ns)
                except Exception:  # noqa: BLE001 — unknown object
                    return False
                return obj.meta.labels.get(c.LABEL_PCS_NAME) == pcs

            def _metrics_push(self):
                """Workload→control-plane metric ingestion: engines inside
                pods report autoscaling signals here; the Autoscaler and
                ServingObserver consume them from the MetricsRegistry.

                Two payload shapes, one scope check: the legacy single
                sample (``{"kind","name","metric","value"}``) and the
                batched form (``{"kind","name","samples":[{"metric",
                "value","agg"?}, ...]}`` — one POST per reporting tick
                carrying an engine's whole SLO digest, each sample
                naming how the registry combines it across reporters).
                All-or-nothing: a malformed sample rejects the batch
                before anything is recorded."""
                if cluster.metrics is None:
                    self._send(503, {"error": "autoscaler disabled"})
                    return
                actor = self._actor()
                if self._auth_config().require_token_for_metrics:
                    if actor is None or actor == ANONYMOUS_ACTOR:
                        self._send(401, {"error": "metrics push requires a "
                                         "bearer token"})
                        return
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    if actor and actor.startswith(
                            c.WORKLOAD_ACTOR_PREFIX) and \
                            not self._workload_owns(actor, payload):
                        self._send(403, {"error":
                                         f"workload actor {actor!r} may "
                                         "only report metrics for its own "
                                         "PodCliqueSet's components"})
                        return
                    if "samples" in payload:
                        samples = []
                        for s in payload["samples"]:
                            if not isinstance(s, dict):
                                # A str here would .get() its way to an
                                # AttributeError past the 400 handler.
                                raise ValueError(
                                    f"sample must be an object, got "
                                    f"{type(s).__name__}")
                            agg = s.get("agg")
                            if agg not in (None, "sum", "max", "avg"):
                                raise ValueError(
                                    f"unknown agg {agg!r} for "
                                    f"{s.get('metric')!r}")
                            samples.append((str(s["metric"]),
                                            float(s["value"]), agg))
                    else:
                        samples = [(payload["metric"],
                                    float(payload["value"]), None)]
                    for metric, value, agg in samples:
                        cluster.metrics.set(
                            payload["kind"], payload["name"], metric,
                            value,
                            namespace=payload.get("namespace", "default"),
                            reporter=payload.get("reporter", "_default"),
                            agg=agg)
                    self._send(200, {"ok": True,
                                     "accepted": len(samples)})
                except (KeyError, TypeError, ValueError) as e:
                    self._send(400, {"error": f"bad metric payload: {e}; "
                                     "need kind/name and metric/value or "
                                     "samples[]"})

            def _status_batch(self, kind: str):
                """POST /batch/<kind>/status — batched status merge
                patches ({"namespace", "items": [{"name", "patch"}]}),
                applied under ONE store lock acquisition so controllers
                coalesce the burst (a kubelet fleet marking a gang Ready
                is hundreds of writes at once; N sequential wire PATCHes
                would hand controllers N wake-ups). Returns one result
                per item: null or {"error"}."""
                cls = self._kind(kind)
                if cls is None:
                    return
                if not self._guard_secret_access(cls):
                    return
                client = self._mutating_client()
                if client is None:
                    return
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    body = json.loads(self.rfile.read(length) or b"")
                    items = [(i["name"], i["patch"]) for i in body["items"]]
                except (ValueError, TypeError, KeyError) as e:
                    self._send(400, {"error": f"bad batch body: {e}"})
                    return
                try:
                    results = client.patch_status_many(
                        cls, items, namespace=body.get("namespace",
                                                       "default"))
                except ForbiddenError as e:
                    self._send(403, {"error": str(e)})
                    return
                self._send(200, {"results": [
                    None if r is None else {"error": str(r)}
                    for r in results]})

            def do_PATCH(self):
                """PATCH /api/<kind>/<name> (spec/labels/annotations merge
                patch) and PATCH /api/<kind>/<name>/status (status-
                subresource merge, conditions by type — the kubelet
                status-write pattern; no rv precondition)."""
                url = urlparse(self.path)
                parts = [p for p in url.path.split("/") if p]
                status_sub = (len(parts) == 4 and parts[3] == "status")
                if not (len(parts) == 3 or status_sub) or parts[0] != "api":
                    self._send(404, {"error":
                                     "PATCH /api/<kind>/<name>[/status]"})
                    return
                cls = self._kind(parts[1])
                if cls is None:
                    return
                if not self._guard_secret_access(cls):
                    return
                client = self._mutating_client()
                if client is None:
                    return
                ns = parse_qs(url.query).get("namespace", ["default"])[0]
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    patch = json.loads(self.rfile.read(length) or b"")
                except ValueError as e:
                    self._send(400, {"error": f"bad patch JSON: {e}"})
                    return
                try:
                    if status_sub:
                        updated = client.patch_status(cls, parts[2], patch,
                                                      namespace=ns)
                    else:
                        updated = client.patch(cls, parts[2], patch,
                                               namespace=ns)
                    self._send(200, to_dict(updated))
                except NotFoundError as e:
                    self._send(404, {"error": str(e)})
                except ForbiddenError as e:
                    self._send(403, {"error": str(e)})
                except ConflictError as e:
                    self._send(409, {"error": str(e)})
                except GroveError as e:
                    self._send(400, {"error": str(e)})

            def do_PUT(self):
                """PUT /api/<kind>/<name>/status — the status-subresource
                write (the remote node agent's path; spec/meta edits in
                the body are ignored by the store, exactly as in-process
                update_status)."""
                url = urlparse(self.path)
                parts = [p for p in url.path.split("/") if p]
                if len(parts) != 4 or parts[0] != "api" \
                        or parts[3] != "status":
                    self._send(404,
                               {"error": "PUT /api/<kind>/<name>/status"})
                    return
                cls = self._kind(parts[1])
                if cls is None:
                    return
                if not self._guard_secret_access(cls):
                    return
                client = self._mutating_client()
                if client is None:
                    return
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    obj = from_dict(cls, json.loads(
                        self.rfile.read(length) or b""))
                except (ValueError, TypeError, KeyError) as e:
                    self._send(400, {"error": f"bad status body: {e}"})
                    return
                if obj.meta.name != parts[2]:
                    self._send(400, {"error": f"body names "
                                     f"{obj.meta.name!r}, URL names "
                                     f"{parts[2]!r}"})
                    return
                try:
                    updated = client.update_status(obj)
                    self._send(200, to_dict(updated))
                except NotFoundError as e:
                    self._send(404, {"error": str(e)})
                except ForbiddenError as e:
                    self._send(403, {"error": str(e)})
                except ConflictError as e:
                    self._send(409, {"error": str(e)})
                except GroveError as e:
                    self._send(400, {"error": str(e)})

            def do_DELETE(self):
                url = urlparse(self.path)
                parts = [p for p in url.path.split("/") if p]
                if len(parts) != 3 or parts[0] != "api":
                    self._send(404, {"error": "DELETE /api/<kind>/<name>"})
                    return
                cls = self._kind(parts[1])
                if cls is None:
                    return
                if not self._guard_secret_access(cls):
                    return
                client = self._mutating_client()
                if client is None:
                    return
                ns = parse_qs(url.query).get("namespace", ["default"])[0]
                try:
                    client.delete(cls, parts[2], ns)
                    self._send(200, {"deleted": parts[2]})
                except NotFoundError as e:
                    self._send(404, {"error": str(e)})
                except ForbiddenError as e:
                    self._send(403, {"error": str(e)})
                except GroveError as e:
                    self._send(400, {"error": str(e)})

        api_server = self

        class QuietServer(ThreadingHTTPServer):
            def handle_error(self, request, client_address):
                # Failed/timed-out TLS handshakes (port scans, plain-HTTP
                # probes, half-open connections) are expected noise, not
                # server errors worth a traceback.
                import ssl
                import sys
                exc = sys.exc_info()[1]
                if api_server._certs is not None and isinstance(
                        exc, (ssl.SSLError, TimeoutError, ConnectionError)):
                    return
                super().handle_error(request, client_address)

        self._httpd = QuietServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._setup_tls()
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="api-server",
            daemon=True)
        self._serve_thread.start()

    def stop(self) -> None:
        self._stopped = True
        if self._rotate_timer is not None:
            self._rotate_timer.cancel()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        # shutdown() returns once serve_forever exits its loop; the
        # join makes "stopped" mean no request thread still touches the
        # manager (grovelint thread-join-in-stop).
        if getattr(self, "_serve_thread", None) is not None:
            self._serve_thread.join(timeout=2.0)
            self._serve_thread = None
