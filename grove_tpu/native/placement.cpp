// Native placement core — gang bin-packing over topology domains.
//
// The computational kernel of the gang scheduler (grove_tpu/scheduler/
// placement.py documents the semantics; this is a drop-in for plan_gang's
// inner search). The reference implements its scheduler role in Go inside
// the operator; here the control plane is Python and the hot placement
// path is C++ behind a C ABI consumed via ctypes.
//
// Semantics mirror placement.plan_gang exactly (property-tested against
// the Python implementation in tests/test_native_placement.py):
//   - candidate domains = distinct host_domain values
//   - first-fit-decreasing of pods onto a domain's hosts (hosts ordered
//     by descending free chips; ties broken by input order)
//   - eligibility mask gates pod->host placements (node selectors)
//   - score = used/total_free - penalty[domain] (+10 for prefer_domain)
//   - required=false falls back to FFD over all hosts (score -1)
//
// Build: g++ -O2 -shared -fPIC placement.cpp -o libplacement.so

#include <algorithm>
#include <cstdint>
#include <vector>

extern "C" {

// Returns: 1 = planned within a domain (*out_domain set), 0 = planned
// across domains (relaxed), -1 = infeasible. out_assignment[i] = host
// index for pod i.
int grove_plan_gang(
    int32_t n_pods, const int64_t* pod_chips,
    int32_t n_hosts, const int64_t* host_free, const int32_t* host_domain,
    const uint8_t* eligible,          // [n_pods * n_hosts] 0/1
    int32_t n_domains, const double* domain_penalty,
    int32_t prefer_domain,            // -1 = none
    int32_t required,
    double* out_score, int32_t* out_domain, int32_t* out_assignment) {

  // Pods sorted by descending chip request (stable).
  std::vector<int32_t> pod_order(n_pods);
  for (int32_t i = 0; i < n_pods; ++i) pod_order[i] = i;
  std::stable_sort(pod_order.begin(), pod_order.end(),
                   [&](int32_t a, int32_t b) {
                     return pod_chips[a] > pod_chips[b];
                   });

  // Hosts by descending free chips (stable), reused per candidate.
  std::vector<int32_t> host_order(n_hosts);
  for (int32_t i = 0; i < n_hosts; ++i) host_order[i] = i;
  std::stable_sort(host_order.begin(), host_order.end(),
                   [&](int32_t a, int32_t b) {
                     return host_free[a] > host_free[b];
                   });

  std::vector<int64_t> free_work(n_hosts);
  std::vector<int32_t> assign_work(n_pods);

  // FFD over an allowed host set; returns true when every pod placed.
  auto ffd = [&](int32_t domain /* -1 = any */) -> bool {
    for (int32_t h = 0; h < n_hosts; ++h) free_work[h] = host_free[h];
    for (int32_t p = 0; p < n_pods; ++p) assign_work[p] = -1;
    for (int32_t pi : pod_order) {
      bool placed = false;
      for (int32_t h : host_order) {
        if (domain >= 0 && host_domain[h] != domain) continue;
        if (free_work[h] < pod_chips[pi]) continue;
        if (!eligible[(size_t)pi * n_hosts + h]) continue;
        assign_work[pi] = h;
        free_work[h] -= pod_chips[pi];
        placed = true;
        break;
      }
      if (!placed) return false;
    }
    return true;
  };

  int64_t used = 0;
  for (int32_t p = 0; p < n_pods; ++p) used += pod_chips[p];

  double best_score = -1e300;
  int32_t best_domain = -1;
  std::vector<int32_t> best_assign;

  for (int32_t d = 0; d < n_domains; ++d) {
    // Skip domains with no hosts.
    int64_t total_free = 0;
    bool has_host = false;
    for (int32_t h = 0; h < n_hosts; ++h) {
      if (host_domain[h] == d) { total_free += host_free[h]; has_host = true; }
    }
    if (!has_host) continue;
    if (!ffd(d)) continue;
    double tightness = total_free > 0 ? (double)used / (double)total_free : 1.0;
    double score = tightness - domain_penalty[d];
    if (d == prefer_domain) score += 10.0;
    if (score > best_score) {
      best_score = score;
      best_domain = d;
      best_assign = assign_work;
    }
  }

  if (best_domain >= 0) {
    *out_score = best_score;
    *out_domain = best_domain;
    for (int32_t p = 0; p < n_pods; ++p) out_assignment[p] = best_assign[p];
    return 1;
  }
  if (required) return -1;
  if (!ffd(-1)) return -1;
  *out_score = -1.0;
  *out_domain = -1;
  for (int32_t p = 0; p < n_pods; ++p) out_assignment[p] = assign_work[p];
  return 0;
}

}  // extern "C"

extern "C" {

// Grouped gang planning — the per-PodGroup-constraint form
// (placement.plan_gang_grouped; reference PodGroup.TopologyConstraint,
// scheduler api podgang.go:99-117). Semantics mirror the Python
// reference exactly (property-tested in tests/test_native_placement.py):
//   - candidate OUTER domains in input-id order; within one domain:
//       constrained groups (descending total demand, stable) each pack
//       into the best sub-domain by tightness against CURRENT free
//       (first-appearance sub-domain order; FFD with hosts re-sorted by
//       current free, stable); a non-required group relaxes to FFD over
//       the whole domain; unconstrained pods fill last.
//   - domain score = used/total_free(original) - penalty (+10 prefer);
//     first max wins.
//   - required=0 falls back to the same procedure across ALL hosts
//     (score -1, no domain).
// group_sub_domain: [n_groups * n_hosts] sub-domain id of each host at
// each group's pack level (-1 entries are never read for unconstrained
// groups; pod_group[i] = -1 marks unconstrained pods).
int grove_plan_gang_grouped(
    int32_t n_pods, const int64_t* pod_chips, const int32_t* pod_group,
    int32_t n_groups, const uint8_t* group_required,
    int32_t n_hosts, const int64_t* host_free, const int32_t* host_domain,
    const int32_t* group_sub_domain,
    const uint8_t* eligible,          // [n_pods * n_hosts] 0/1
    int32_t n_domains, const double* domain_penalty,
    int32_t prefer_domain,            // -1 = none
    int32_t required,
    double* out_score, int32_t* out_domain, int32_t* out_assignment) {

  std::vector<int64_t> group_demand(n_groups, 0);
  std::vector<std::vector<int32_t>> group_pods(n_groups);
  std::vector<int32_t> rest_pods;
  for (int32_t p = 0; p < n_pods; ++p) {
    int32_t g = pod_group[p];
    if (g >= 0) {
      group_demand[g] += pod_chips[p];
      group_pods[g].push_back(p);
    } else {
      rest_pods.push_back(p);
    }
  }
  // Constrained groups by descending demand (stable on input order).
  std::vector<int32_t> group_order;
  for (int32_t g = 0; g < n_groups; ++g) group_order.push_back(g);
  std::stable_sort(group_order.begin(), group_order.end(),
                   [&](int32_t a, int32_t b) {
                     return group_demand[a] > group_demand[b];
                   });

  std::vector<int64_t> free_work(n_hosts);
  std::vector<int32_t> assign_work(n_pods);

  // FFD of `pods` (sorted by descending chips, stable) onto `cand`
  // hosts re-sorted by CURRENT free (stable). Mutates free_work /
  // assign_work; returns false (and leaves partial state for the
  // caller to discard) when any pod cannot place.
  auto ffd_into = [&](const std::vector<int32_t>& pods,
                      std::vector<int32_t> cand) -> bool {
    std::stable_sort(cand.begin(), cand.end(),
                     [&](int32_t a, int32_t b) {
                       return free_work[a] > free_work[b];
                     });
    std::vector<int32_t> order(pods);
    std::stable_sort(order.begin(), order.end(),
                     [&](int32_t a, int32_t b) {
                       return pod_chips[a] > pod_chips[b];
                     });
    for (int32_t pi : order) {
      bool placed = false;
      for (int32_t h : cand) {
        if (free_work[h] < pod_chips[pi]) continue;
        if (!eligible[(size_t)pi * n_hosts + h]) continue;
        assign_work[pi] = h;
        free_work[h] -= pod_chips[pi];
        placed = true;
        break;
      }
      if (!placed) return false;
    }
    return true;
  };

  // Plan every group + the rest into the host set `domain` (-1 = all).
  // Returns true when everything placed.
  auto plan_in = [&](int32_t domain) -> bool {
    for (int32_t h = 0; h < n_hosts; ++h) free_work[h] = host_free[h];
    for (int32_t p = 0; p < n_pods; ++p) assign_work[p] = -1;
    std::vector<int32_t> dom_hosts;
    for (int32_t h = 0; h < n_hosts; ++h)
      if (domain < 0 || host_domain[h] == domain) dom_hosts.push_back(h);
    for (int32_t g : group_order) {
      if (group_pods[g].empty()) continue;
      // Candidate sub-domains in first-appearance order.
      std::vector<int32_t> subs;
      for (int32_t h : dom_hosts) {
        int32_t s = group_sub_domain[(size_t)g * n_hosts + h];
        bool seen = false;
        for (int32_t x : subs) if (x == s) { seen = true; break; }
        if (!seen) subs.push_back(s);
      }
      double best_score = -1e300;
      std::vector<int64_t> best_free;
      std::vector<int32_t> best_assign;
      bool found = false;
      std::vector<int64_t> save_free(free_work);
      std::vector<int32_t> save_assign(assign_work);
      for (int32_t s : subs) {
        std::vector<int32_t> cand;
        int64_t total_free = 0;
        for (int32_t h : dom_hosts)
          if (group_sub_domain[(size_t)g * n_hosts + h] == s) {
            cand.push_back(h);
            total_free += free_work[h];
          }
        free_work = save_free;
        assign_work = save_assign;
        if (!ffd_into(group_pods[g], cand)) continue;
        double tightness = total_free > 0
            ? (double)group_demand[g] / (double)total_free : 1.0;
        if (tightness > best_score) {
          best_score = tightness;
          best_free = free_work;
          best_assign = assign_work;
          found = true;
        }
      }
      if (found) {
        free_work = best_free;
        assign_work = best_assign;
        continue;
      }
      free_work = save_free;
      assign_work = save_assign;
      if (group_required[g]) return false;
      if (!ffd_into(group_pods[g], dom_hosts)) return false;  // relax
    }
    if (!rest_pods.empty() && !ffd_into(rest_pods, dom_hosts)) return false;
    return true;
  };

  int64_t used = 0;
  for (int32_t p = 0; p < n_pods; ++p) used += pod_chips[p];

  double best_score = -1e300;
  int32_t best_domain = -1;
  std::vector<int32_t> best_assign;
  for (int32_t d = 0; d < n_domains; ++d) {
    int64_t total_free = 0;
    bool has_host = false;
    for (int32_t h = 0; h < n_hosts; ++h)
      if (host_domain[h] == d) { total_free += host_free[h]; has_host = true; }
    if (!has_host) continue;
    if (!plan_in(d)) continue;
    double tightness = total_free > 0
        ? (double)used / (double)total_free : 1.0;
    double score = tightness - domain_penalty[d];
    if (d == prefer_domain) score += 10.0;
    if (score > best_score) {
      best_score = score;
      best_domain = d;
      best_assign = assign_work;
    }
  }
  if (best_domain >= 0) {
    *out_score = best_score;
    *out_domain = best_domain;
    for (int32_t p = 0; p < n_pods; ++p) out_assignment[p] = best_assign[p];
    return 1;
  }
  if (required) return -1;
  if (!plan_in(-1)) return -1;
  *out_score = -1.0;
  *out_domain = -1;
  for (int32_t p = 0; p < n_pods; ++p) out_assignment[p] = assign_work[p];
  return 0;
}

}  // extern "C"
