"""ctypes loader for the native placement core.

Compiles grove_tpu/native/placement.cpp with the system toolchain on
first use (cached next to the source); every entry point degrades to the
pure-Python implementation when no compiler is available, so the control
plane never hard-depends on the native build.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from grove_tpu.runtime.logger import get_logger

log = get_logger("native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "placement.cpp")
_LIB = os.path.join(_HERE, "libplacement.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O2", "-shared", "-fPIC", _SRC, "-o", _LIB]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.info("native placement build unavailable (%s); using python "
                 "fallback", e)
        return False


def _load_nowait() -> Optional[ctypes.CDLL]:
    """Non-blocking view for the placement hot path: while a build holds
    the lock (prewarm compiling), callers fall back to Python instead of
    stalling behind g++."""
    if _lib is not None:
        return _lib
    if not _lock.acquire(blocking=False):
        return None
    try:
        return _load_locked()
    finally:
        _lock.release()


def _load() -> Optional[ctypes.CDLL]:
    with _lock:
        return _load_locked()


def _load_locked() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    have_lib = os.path.exists(_LIB)
    have_src = os.path.exists(_SRC)
    stale = (have_lib and have_src
             and os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
    if not have_lib or stale:
        # No source (pruned install with a prebuilt .so is fine; with
        # neither, fall back to Python) -> don't try to compile.
        if not have_src or not _build():
            if not have_lib:
                return None
            if stale:
                # A stale binary would silently diverge from the Python
                # reference semantics — never load it.
                log.warning(
                    "libplacement.so is older than placement.cpp and "
                    "rebuild failed; using the python implementation")
                return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError as e:
        log.info("native placement load failed (%s)", e)
        return None
    lib.grove_plan_gang.restype = ctypes.c_int
    lib.grove_plan_gang.argtypes = [
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int32, ctypes.POINTER(ctypes.c_double),
        ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


def prewarm(background: bool = True) -> None:
    """Trigger the (possibly compiling) load off the hot path — the gang
    backend calls this at init so the first placement pass never stalls
    behind a g++ invocation."""
    if background:
        threading.Thread(target=_load, name="native-prewarm",
                         daemon=True).start()
    else:
        _load()


def native_plan_gang(pods, hosts, pack_level: str, required: bool,
                     prefer_slice: str, spread_penalty: dict[str, float]):
    """Native-backed equivalent of placement.plan_gang. Returns a
    PlacementPlan or None (infeasible), or NotImplemented when the native
    library is unavailable (caller falls back to Python)."""
    lib = _load_nowait()
    if lib is None:
        return NotImplemented

    from grove_tpu.scheduler.placement import (
        PlacementPlan,
        _domain_of,
        _selector_matches,
    )

    n_pods = len(pods)
    n_hosts = len(hosts)
    if n_pods == 0:
        return PlacementPlan({}, "", 0.0)
    if n_hosts == 0:
        return None

    level = pack_level or "slice"
    domain_names: list[str] = []
    domain_ids: dict[str, int] = {}
    host_domain = (ctypes.c_int32 * n_hosts)()
    host_free = (ctypes.c_int64 * n_hosts)()
    for h_i, h in enumerate(hosts):
        dom = _domain_of(h, level)
        if dom not in domain_ids:
            domain_ids[dom] = len(domain_names)
            domain_names.append(dom)
        host_domain[h_i] = domain_ids[dom]
        host_free[h_i] = h.free_chips

    pod_chips = (ctypes.c_int64 * n_pods)()
    eligible = (ctypes.c_uint8 * (n_pods * n_hosts))()
    for p_i, p in enumerate(pods):
        pod_chips[p_i] = p.chips
        for h_i, h in enumerate(hosts):
            # ONE eligibility definition for both planners: the python
            # matcher owns selector + reservation-taint semantics.
            eligible[p_i * n_hosts + h_i] = \
                1 if _selector_matches(p, h) else 0

    n_domains = len(domain_names)
    penalty = (ctypes.c_double * n_domains)()
    for name, p in (spread_penalty or {}).items():
        if name in domain_ids:
            penalty[domain_ids[name]] = p
    prefer = domain_ids.get(prefer_slice, -1) if prefer_slice else -1

    out_score = ctypes.c_double()
    out_domain = ctypes.c_int32()
    out_assign = (ctypes.c_int32 * n_pods)()
    rc = lib.grove_plan_gang(
        n_pods, pod_chips, n_hosts, host_free, host_domain, eligible,
        n_domains, penalty, prefer, 1 if required else 0,
        ctypes.byref(out_score), ctypes.byref(out_domain), out_assign)
    if rc < 0:
        return None
    assignment = {pods[i].name: hosts[out_assign[i]].name
                  for i in range(n_pods)}
    if rc == 1:
        dom = domain_names[out_domain.value]
        slice_name = dom if level == "slice" else ""
    else:
        slice_name = ""
    return PlacementPlan(assignment, slice_name, out_score.value)
