"""ctypes loader for the native placement core.

Compiles grove_tpu/native/placement.cpp with the system toolchain on
first use (cached next to the source); every entry point degrades to the
pure-Python implementation when no compiler is available, so the control
plane never hard-depends on the native build.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from grove_tpu.runtime.logger import get_logger

log = get_logger("native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "placement.cpp")
_LIB = os.path.join(_HERE, "libplacement.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O2", "-shared", "-fPIC", _SRC, "-o", _LIB]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.info("native placement build unavailable (%s); using python "
                 "fallback", e)
        return False


def _load_nowait() -> Optional[ctypes.CDLL]:
    """Non-blocking view for the placement hot path: while a build holds
    the lock (prewarm compiling), callers fall back to Python instead of
    stalling behind g++."""
    if _lib is not None:
        return _lib
    if not _lock.acquire(blocking=False):
        return None
    try:
        return _load_locked()
    finally:
        _lock.release()


def _load() -> Optional[ctypes.CDLL]:
    with _lock:
        return _load_locked()


def _load_locked() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    have_lib = os.path.exists(_LIB)
    have_src = os.path.exists(_SRC)
    stale = (have_lib and have_src
             and os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
    if not have_lib or stale:
        # No source (pruned install with a prebuilt .so is fine; with
        # neither, fall back to Python) -> don't try to compile.
        if not have_src or not _build():
            if not have_lib:
                return None
            if stale:
                # A stale binary would silently diverge from the Python
                # reference semantics — never load it.
                log.warning(
                    "libplacement.so is older than placement.cpp and "
                    "rebuild failed; using the python implementation")
                return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError as e:
        log.info("native placement load failed (%s)", e)
        return None
    lib.grove_plan_gang.restype = ctypes.c_int
    lib.grove_plan_gang.argtypes = [
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int32, ctypes.POINTER(ctypes.c_double),
        ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    if hasattr(lib, "grove_plan_gang_grouped"):
        lib.grove_plan_gang_grouped.restype = ctypes.c_int
        lib.grove_plan_gang_grouped.argtypes = [
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int32, ctypes.POINTER(ctypes.c_double),
            ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


def prewarm(background: bool = True) -> None:
    """Trigger the (possibly compiling) load off the hot path — the gang
    backend calls this at init so the first placement pass never stalls
    behind a g++ invocation."""
    if background:
        threading.Thread(target=_load, name="native-prewarm",
                         daemon=True).start()
    else:
        _load()




def _marshal_hosts(hosts, level: str):
    """Shared host/domain marshalling for both planners (one copy of the
    domain-id assignment — first-appearance order — so the two wrappers
    can never desynchronize)."""
    from grove_tpu.scheduler.placement import _domain_of
    n_hosts = len(hosts)
    domain_names: list[str] = []
    domain_ids: dict[str, int] = {}
    host_domain = (ctypes.c_int32 * n_hosts)()
    host_free = (ctypes.c_int64 * n_hosts)()
    for h_i, h in enumerate(hosts):
        dom = _domain_of(h, level)
        if dom not in domain_ids:
            domain_ids[dom] = len(domain_names)
            domain_names.append(dom)
        host_domain[h_i] = domain_ids[dom]
        host_free[h_i] = h.free_chips
    return domain_names, domain_ids, host_domain, host_free


def _marshal_eligibility(pods, hosts):
    """ONE eligibility definition for all planners: the python matcher
    owns selector + reservation-taint semantics."""
    from grove_tpu.scheduler.placement import _selector_matches
    n_pods, n_hosts = len(pods), len(hosts)
    pod_chips = (ctypes.c_int64 * max(1, n_pods))()
    eligible = (ctypes.c_uint8 * max(1, n_pods * n_hosts))()
    for p_i, p in enumerate(pods):
        pod_chips[p_i] = p.chips
        for h_i, h in enumerate(hosts):
            eligible[p_i * n_hosts + h_i] = \
                1 if _selector_matches(p, h) else 0
    return pod_chips, eligible


def _marshal_scoring(domain_names, domain_ids, spread_penalty,
                     prefer_slice):
    penalty = (ctypes.c_double * max(1, len(domain_names)))()
    for name, pen in (spread_penalty or {}).items():
        if name in domain_ids:
            penalty[domain_ids[name]] = pen
    prefer = domain_ids.get(prefer_slice, -1) if prefer_slice else -1
    return penalty, prefer


def _decode_plan(rc, pods, hosts, domain_names, level,
                 out_score, out_domain, out_assign):
    from grove_tpu.scheduler.placement import PlacementPlan
    if rc < 0:
        return None
    assignment = {pods[i].name: hosts[out_assign[i]].name
                  for i in range(len(pods))}
    if rc == 1:
        dom = domain_names[out_domain.value]
        slice_name = dom if level == "slice" else ""
    else:
        slice_name = ""
    return PlacementPlan(assignment, slice_name, out_score.value)


def native_plan_gang(pods, hosts, pack_level: str, required: bool,
                     prefer_slice: str, spread_penalty: dict[str, float]):
    """Native-backed equivalent of placement.plan_gang. Returns a
    PlacementPlan or None (infeasible), or NotImplemented when the native
    library is unavailable (caller falls back to Python)."""
    lib = _load_nowait()
    if lib is None:
        return NotImplemented

    from grove_tpu.scheduler.placement import PlacementPlan

    n_pods = len(pods)
    n_hosts = len(hosts)
    if n_pods == 0:
        return PlacementPlan({}, "", 0.0)
    if n_hosts == 0:
        return None

    level = pack_level or "slice"
    domain_names, domain_ids, host_domain, host_free = \
        _marshal_hosts(hosts, level)
    pod_chips, eligible = _marshal_eligibility(pods, hosts)
    penalty, prefer = _marshal_scoring(domain_names, domain_ids,
                                       spread_penalty, prefer_slice)

    out_score = ctypes.c_double()
    out_domain = ctypes.c_int32()
    out_assign = (ctypes.c_int32 * n_pods)()
    rc = lib.grove_plan_gang(
        n_pods, pod_chips, n_hosts, host_free, host_domain, eligible,
        len(domain_names), penalty, prefer, 1 if required else 0,
        ctypes.byref(out_score), ctypes.byref(out_domain), out_assign)
    return _decode_plan(rc, pods, hosts, domain_names, level,
                        out_score, out_domain, out_assign)


def native_plan_gang_grouped(groups, hosts, pack_level: str,
                             required: bool, prefer_slice: str,
                             spread_penalty: dict[str, float]):
    """Native-backed equivalent of placement.plan_gang_grouped. Returns
    a PlacementPlan or None (infeasible), or NotImplemented when the
    native library is unavailable. No zero-pod early return: the kernel
    reproduces the reference's scoring for empty gangs too (prefer
    bonus / penalties still pick the slice a rolling update would
    reuse)."""
    lib = _load_nowait()
    if lib is None or not hasattr(lib, "grove_plan_gang_grouped"):
        return NotImplemented

    from grove_tpu.scheduler.placement import _domain_of

    pods = [p for g in groups for p in g.pods]
    n_pods = len(pods)
    n_hosts = len(hosts)
    if n_hosts == 0:
        return None

    level = pack_level or "slice"
    domain_names, domain_ids, host_domain, host_free = \
        _marshal_hosts(hosts, level)
    pod_chips, eligible = _marshal_eligibility(pods, hosts)
    penalty, prefer = _marshal_scoring(domain_names, domain_ids,
                                       spread_penalty, prefer_slice)

    constrained = [g for g in groups if g.pack_level]
    n_groups = len(constrained)
    group_required = (ctypes.c_uint8 * max(1, n_groups))()
    group_sub = (ctypes.c_int32 * max(1, n_groups * n_hosts))()
    group_of = {}
    for g_i, g in enumerate(constrained):
        group_required[g_i] = 1 if g.required else 0
        sub_ids: dict[str, int] = {}
        for h_i, h in enumerate(hosts):
            sub = _domain_of(h, g.pack_level)
            if sub not in sub_ids:
                sub_ids[sub] = len(sub_ids)
            group_sub[g_i * n_hosts + h_i] = sub_ids[sub]
        for p in g.pods:
            group_of[p.name] = g_i

    pod_group = (ctypes.c_int32 * max(1, n_pods))()
    for p_i, p in enumerate(pods):
        pod_group[p_i] = group_of.get(p.name, -1)

    out_score = ctypes.c_double()
    out_domain = ctypes.c_int32()
    out_assign = (ctypes.c_int32 * max(1, n_pods))()
    rc = lib.grove_plan_gang_grouped(
        n_pods, pod_chips, pod_group, n_groups, group_required,
        n_hosts, host_free, host_domain, group_sub, eligible,
        len(domain_names), penalty, prefer, 1 if required else 0,
        ctypes.byref(out_score), ctypes.byref(out_domain), out_assign)
    return _decode_plan(rc, pods, hosts, domain_names, level,
                        out_score, out_domain, out_assign)
