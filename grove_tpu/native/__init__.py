from grove_tpu.native.loader import native_available, native_plan_gang

__all__ = ["native_available", "native_plan_gang"]
