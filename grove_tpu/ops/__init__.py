from grove_tpu.ops.norms import rms_norm
from grove_tpu.ops.rope import apply_rope, rope_table
from grove_tpu.ops.attention import causal_attention, decode_attention
from grove_tpu.ops.kvcache import KVCache

__all__ = [
    "rms_norm",
    "apply_rope",
    "rope_table",
    "causal_attention",
    "decode_attention",
    "KVCache",
]
