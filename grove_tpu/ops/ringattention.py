"""Ring attention: causal attention with sequence parallelism over ICI.

Long-context first-class support: the sequence axis is sharded over the
``sp`` mesh axis; Q stays resident while K/V blocks rotate around the
ring via ``lax.ppermute`` (nearest-neighbour ICI hops — exactly the
traffic pattern the orchestrator's slice-atomic gang placement
guarantees can form). Per-block results merge with the online-softmax
(log-sum-exp) rule, so memory stays O(seq_local) regardless of total
sequence length.

The reference operator never touches sequence length (SURVEY.md §5) —
its role is packing the participants onto one fabric; this module is the
in-pod half of that contract.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:                       # moved to the top level in newer jax
    from jax import shard_map as _shard_map
except ImportError:
    # jax <= 0.4.x keeps it under experimental, where the replication
    # checker predates varying types and rejects valid bodies (e.g. a
    # cond over freshly-built accumulators) — disable it there; newer
    # jax type-checks the same bodies natively.
    from jax.experimental.shard_map import shard_map as _esm

    def _shard_map(f, **kw):
        return _esm(f, check_rep=False, **kw)



def _pcast_varying(x, axes):
    # lax.pcast's varying-type marking exists only in newer jax; the
    # 0.4.x shard_map has no varying types, so identity is exact there.
    pcast = getattr(lax, "pcast", None)
    return pcast(x, axes, to="varying") if pcast is not None else x


def _axis_size(name):
    # lax.axis_size is newer-jax; psum(1, axis) is the classic idiom it
    # replaced and constant-folds to the same static size under shard_map.
    size = getattr(lax, "axis_size", None)
    return size(name) if size is not None else lax.psum(1, name)

from grove_tpu.parallel.mesh import AXIS_DP, AXIS_SP, AXIS_TP

NEG_INF = -1e30


def _block_attention(q, k, v, q_offset, kv_offset, scale):
    """Attention of local Q against one K/V block, returning the
    un-normalised accumulator pieces (max, exp-sum, weighted values).

    q: [b, sq, h, d]; k/v: [b, sk, n_kv, d] (GQA: h = n_kv * group).
    """
    b, sq, h, d = q.shape
    n_kv = k.shape[2]
    qg = q.reshape(b, sq, n_kv, h // n_kv, d)
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qg * scale, k,
                        preferred_element_type=jnp.float32)
    q_pos = jnp.arange(sq)[:, None] + q_offset
    kv_pos = jnp.arange(k.shape[1])[None, :] + kv_offset
    mask = q_pos >= kv_pos
    logits = jnp.where(mask[None, None, None], logits, NEG_INF)
    block_max = jnp.max(logits, axis=-1)                    # [b,k,g,q]
    probs = jnp.exp(logits - block_max[..., None])
    # Fully-masked rows: block_max == NEG_INF -> make their contribution 0.
    probs = jnp.where((block_max == NEG_INF)[..., None], 0.0, probs)
    block_sum = jnp.sum(probs, axis=-1)                     # [b,k,g,q]
    block_out = jnp.einsum("bkgqs,bskd->bkgqd", probs, v.astype(jnp.float32))
    return block_max, block_sum, block_out


def _ring_attention_local(q, k, v, axis_name: str):
    """Per-shard body (run under shard_map): rotate K/V around the ring."""
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    n_kv = k.shape[2]
    scale = d ** -0.5
    q_offset = idx * sq

    # Mark the fresh accumulators as device-varying so the loop carry
    # types match after they mix with per-shard data.
    all_axes = (AXIS_DP, AXIS_SP, AXIS_TP)

    def _varying(x):
        return _pcast_varying(x, all_axes)

    acc_max = _varying(jnp.full((b, n_kv, h // n_kv, sq), NEG_INF, jnp.float32))
    acc_sum = _varying(jnp.zeros((b, n_kv, h // n_kv, sq), jnp.float32))
    acc_out = _varying(jnp.zeros((b, n_kv, h // n_kv, sq, d), jnp.float32))

    def body(step, carry):
        acc_max, acc_sum, acc_out, k, v = carry
        # Blocks rotate i -> i+1 each step, so at step s this shard holds
        # the block that started (s shards) behind it — progressively
        # older blocks, which is exactly the causal-friendly order.
        src = (idx - step) % n
        kv_offset = src * k.shape[1]

        # Blocks entirely in the causal future contribute nothing; skip
        # their attention FLOPs (~(n-1)/2n of all blocks). The ppermute
        # below still runs every step, so the collective stays uniform
        # across shards.
        def compute(_):
            return _block_attention(q, k, v, q_offset, kv_offset, scale)

        def skip(_):
            g = h // n_kv
            return (_varying(jnp.full((b, n_kv, g, sq), NEG_INF, jnp.float32)),
                    _varying(jnp.zeros((b, n_kv, g, sq), jnp.float32)),
                    _varying(jnp.zeros((b, n_kv, g, sq, d), jnp.float32)))

        block_in_past = src * k.shape[1] <= q_offset + sq - 1
        bmax, bsum, bout = lax.cond(block_in_past, compute, skip, None)
        new_max = jnp.maximum(acc_max, bmax)
        # Guard against (-inf) - (-inf) when a row has seen nothing yet.
        corr_old = jnp.exp(jnp.where(acc_max == NEG_INF, NEG_INF,
                                     acc_max - new_max))
        corr_new = jnp.exp(jnp.where(bmax == NEG_INF, NEG_INF,
                                     bmax - new_max))
        acc_sum = acc_sum * corr_old + bsum * corr_new
        acc_out = acc_out * corr_old[..., None] + bout * corr_new[..., None]
        # Rotate K/V to the next shard (nearest-neighbour ICI hop).
        perm = [(i, (i + 1) % n) for i in range(n)]
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        return new_max, acc_sum, acc_out, k, v

    acc_max, acc_sum, acc_out, _, _ = lax.fori_loop(
        0, n, body, (acc_max, acc_sum, acc_out, k, v))
    out = acc_out / jnp.maximum(acc_sum[..., None], 1e-30)
    # [b, k, g, q, d] -> [b, q, h, d]
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, sq, h, d)
    return out.astype(q.dtype)


def ring_attention(mesh: Mesh, q, k, v, *, axis_name: str = AXIS_SP):
    """Causal GQA ring attention over the ``sp`` mesh axis.

    q: [b, s, h, d], k/v: [b, s, n_kv, d] — global shapes; s is sharded
    over ``sp``, h/n_kv over ``tp``, b over ``dp``.
    """
    qspec = P(AXIS_DP, axis_name, AXIS_TP, None)
    fn = _shard_map(
        partial(_ring_attention_local, axis_name=axis_name),
        mesh=mesh,
        in_specs=(qspec, qspec, qspec),
        out_specs=qspec,
    )
    return fn(q, k, v)
