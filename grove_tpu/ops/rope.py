"""Rotary position embeddings.

Precompute the cos/sin table once (host-side, outside jit when possible)
and gather rows by position — avoids recomputing sin/cos per step in the
decode loop.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def rope_table(max_len: int, head_dim: int, theta: float = 10000.0,
               dtype=jnp.float32) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Return (cos, sin), each [max_len, head_dim//2]."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (np.arange(0, half, dtype=np.float32) / half))
    pos = np.arange(max_len, dtype=np.float32)
    ang = np.outer(pos, freqs)
    return jnp.asarray(np.cos(ang), dtype=dtype), jnp.asarray(np.sin(ang), dtype=dtype)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
               positions: jnp.ndarray) -> jnp.ndarray:
    """Rotate ``x`` [..., seq, heads, head_dim] by per-token ``positions`` [..., seq].

    Uses the split-halves ("rotate-half" / GPT-NeoX) convention: dimension
    ``i`` pairs with ``i + head_dim//2``. Meta-Llama checkpoints use the
    interleaved (2i, 2i+1) pairing — a checkpoint importer must permute
    wq/wk columns to this layout (the standard HF conversion).
    """
    c = cos[positions][..., None, :]  # [..., seq, 1, half]
    s = sin[positions][..., None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)
