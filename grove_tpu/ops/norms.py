"""Normalisation ops. Compute in f32, cast back — cheap on VPU, and XLA
fuses the whole norm into neighbouring ops."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)
