"""Pallas flash attention (TPU): causal GQA prefill attention.

The MXU/VMEM-shaped hot op: the grid walks (batch, head, q-block,
k-block); only one Q block and one K/V block are VMEM-resident at a time
(VMEM use is O(block·d), independent of sequence length), with the
online-softmax state carried across k-steps in VMEM scratch. Causally
future k-blocks skip their compute entirely (`pl.when`). f32
accumulation, bf16 matmuls on the MXU.

Same signature/semantics as ops.attention.causal_attention (which
remains the XLA fallback); `interpret=True` runs the kernel on CPU for
tests. See /opt/skills/guides/pallas_guide.md for the programming model.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  block_q: int, block_k: int, scale: float, n_k: int):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: skip k-blocks entirely in the future of this q-block.
    @pl.when(ki * block_k <= qi * block_q + block_q - 1)
    def _compute():
        q = q_ref[0, 0, :, :].astype(jnp.float32) * scale      # [bq, d]
        k = k_ref[0, 0, :, :].astype(jnp.float32)              # [bk, d]
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        kv_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s = jnp.where(q_pos >= kv_pos, s, NEG_INF)
        m_prev = m_ref[:]
        block_m = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, block_m)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where((block_m == NEG_INF)[:, None], 0.0, p)
        corr = jnp.exp(jnp.where(m_prev == NEG_INF, NEG_INF, m_prev - m_new))
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * corr[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _finalize():
        out = acc_ref[:] / jnp.maximum(l_ref[:][:, None], 1e-30)
        o_ref[0, 0, :, :] = out.astype(o_ref.dtype)


def flash_causal_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                           *, block_q: int = 128, block_k: int = 128,
                           interpret: bool = False) -> jnp.ndarray:
    """Causal GQA flash attention. q: [b, s, h, d]; k/v: [b, s, n_kv, d]."""
    b, s, h, d = q.shape
    n_kv = k.shape[2]
    group = h // n_kv
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    assert s % block_q == 0 and s % block_k == 0, \
        f"seq {s} must divide block sizes ({block_q}, {block_k})"
    n_k = s // block_k

    grid = (b, h, s // block_q, n_k)
    kernel = functools.partial(_flash_kernel, block_q=block_q,
                               block_k=block_k, scale=d ** -0.5, n_k=n_k)
    # Head-major layout: Mosaic requires a block's LAST TWO dims to be
    # (divisible by 8, divisible by 128) or equal to the array dims. In
    # the model's native [b, s, h, d] a per-head block is (1, bq, 1, d)
    # whose trailing (1, d) violates the sublane rule for h > 1, so the
    # wrapper transposes to [b/n_kv-heads-major] once outside the kernel
    # and blocks become (1, 1, bq, d) — trailing (bq, d) = (128, 128).
    qt = q.transpose(0, 2, 1, 3)   # [b, h, s, d]
    kt = k.transpose(0, 2, 1, 3)   # [b, n_kv, s, d]
    vt = v.transpose(0, 2, 1, 3)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki, g=group: (bi, hi // g, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki, g=group: (bi, hi // g, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)
