"""Ulysses (all-to-all) sequence parallelism — the second SP strategy.

DeepSpeed-Ulysses-style context parallelism: activations arrive
seq-sharded over ``sp``; one ``lax.all_to_all`` re-shards heads over
``sp`` and assembles the FULL sequence on every member, local causal
attention runs on the head subset, and the inverse all_to_all restores
seq sharding. Versus ring attention (ops/ringattention.py):

- two all_to_all collectives total instead of ``sp`` ppermute rounds —
  fewer, larger transfers that ride ICI's bisection rather than hop
  neighbour-to-neighbour, and no per-step collective latency on the
  critical path;
- the full [b, s, h/sp, d] sequence is resident per member, so memory
  is O(s) (ring stays O(s/sp)) — the right trade for moderate contexts
  where attention FLOPs, not activation memory, dominate;
- heads must divide over sp (GQA: KV heads too) — ring has no such
  constraint.

Both strategies present the same (mesh, q, k, v) surface and both rely
on the orchestrator's slice-atomic placement to keep the sp group on one
ICI domain (SURVEY.md §2.7: the operator packs the participants; the
engine inside the pods runs the actual SP).
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:                       # moved to the top level in newer jax
    from jax import shard_map as _shard_map
except ImportError:        # jax <= 0.4.x keeps it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map


def _axis_size(name):
    # lax.axis_size is newer-jax; psum(1, axis) is the classic idiom it
    # replaced and constant-folds to the same static size under shard_map.
    size = getattr(lax, "axis_size", None)
    return size(name) if size is not None else lax.psum(1, name)

from grove_tpu.ops.attention import causal_attention
from grove_tpu.parallel.mesh import AXIS_DP, AXIS_SP, AXIS_TP


def _ulysses_local(q, k, v, axis_name: str):
    """Per-shard body (under shard_map).

    q: [b, s_local, h_l, d]; k/v: [b, s_local, n_kv_l, d]. h_l/n_kv_l are
    the per-member head counts AFTER any tp sharding; sp further divides
    them for the attention phase.
    """
    sp = _axis_size(axis_name)
    h_l, n_kv_l = q.shape[2], k.shape[2]
    assert h_l % sp == 0 and n_kv_l % sp == 0, (
        f"ulysses needs heads divisible by sp={sp}: have q heads {h_l}, "
        f"kv heads {n_kv_l} per member (use ring attention otherwise)")
    # Gather sequence, scatter heads: [b, s_l, h_l, d] -> [b, s, h_l/sp, d].
    # Shards hold contiguous sequence blocks in axis-index order, so the
    # concat along seq reassembles absolute positions 0..s-1.
    qf = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    kf = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    vf = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    out = causal_attention(qf, kf, vf)           # [b, s, h_l/sp, d]
    # Inverse: gather heads, scatter sequence.
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(mesh: Mesh, q, k, v, *, axis_name: str = AXIS_SP):
    """Causal GQA attention with all-to-all sequence parallelism.

    q: [b, s, h, d], k/v: [b, s, n_kv, d] — global shapes; s sharded over
    ``sp``, heads over ``tp``, batch over ``dp`` (same contract as
    ring_attention)."""
    spec = P(AXIS_DP, axis_name, AXIS_TP, None)
    fn = _shard_map(
        partial(_ulysses_local, axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
