"""Attention ops: prefill (full causal) and decode (single-token vs cache).

Shapes follow the [batch, seq, heads, head_dim] convention throughout; GQA
is handled by repeating KV heads up to Q heads with a reshape-free einsum
grouping (no materialised repeat).

The prefill path is a plain jnp formulation — XLA fuses the softmax chain
and tiles the two matmuls onto the MXU; a pallas flash kernel can be slotted
in behind the same signature (see grove_tpu/ops/pallas/).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def on_tpu() -> bool:
    """True when the default backend is a TPU (incl. the tunnelled relay
    platform, whose platform string differs but whose devices are TPUs)."""
    try:
        dev = jax.devices()[0]
    except Exception:
        return False
    return (dev.platform in ("tpu", "axon")
            or "tpu" in getattr(dev, "device_kind", "").lower())


def pick_causal_attention(seq: int, head_dim: int,
                          q_offset: jnp.ndarray | int = 0):
    """Choose the prefill attention impl for the current backend.

    Returns ``None`` to use the XLA ``causal_attention`` path, or a
    callable ``(q, k, v) -> out`` running the pallas flash kernel
    (grove_tpu/ops/pallas_flash.py) when the backend is a TPU and the
    shape fits the kernel's tiling. ``GROVE_FLASH_ATTENTION=0`` forces
    XLA; ``=1`` forces the kernel (interpret mode off-TPU — slow, for
    parity checks only). Selection happens at trace time, so the choice
    is baked into the compiled executable.
    """
    env = os.environ.get("GROVE_FLASH_ATTENTION", "auto")
    if env == "0":
        return None
    # The kernel derives its causal mask from absolute positions starting
    # at 0 and tiles seq into equal blocks; head_dim rides the MXU lanes.
    if not isinstance(q_offset, int) or q_offset != 0:
        return None
    # seq must tile into full 128-blocks: shorter/unaligned shapes would
    # hand Mosaic a block that violates its (sublane, lane) tiling. All
    # serving paths pad to max_seq_len, a multiple of 128 for every config.
    if seq % 128 != 0 or head_dim % 8 != 0:
        return None
    tpu = on_tpu()
    if env != "1" and not tpu:
        return None
    from grove_tpu.ops.pallas_flash import flash_causal_attention
    interpret = not tpu

    def attn(q, k, v):
        return flash_causal_attention(q, k, v, interpret=interpret)

    attn.impl_name = "pallas-flash" + ("-interpret" if interpret else "")
    return attn


def active_prefill_attention(seq: int, head_dim: int) -> str:
    """Name of the impl ``pick_causal_attention`` would select (for logs)."""
    fn = pick_causal_attention(seq, head_dim)
    return getattr(fn, "impl_name", "xla") if fn is not None else "xla"


def _group_heads(q: jnp.ndarray, n_kv: int) -> jnp.ndarray:
    """[b, s, h, d] -> [b, s, n_kv, group, d] view for GQA."""
    b, s, h, d = q.shape
    return q.reshape(b, s, n_kv, h // n_kv, d)


def causal_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     *, q_offset: jnp.ndarray | int = 0) -> jnp.ndarray:
    """Causal GQA attention for prefill.

    q: [b, sq, h, d]; k, v: [b, skv, n_kv, d]. ``q_offset`` is the absolute
    position of q[0] (for chunked prefill against a longer KV prefix) —
    either a scalar shared by the whole batch or a per-sequence ``[b]``
    vector (speculative verify chunks, where each sequence sits at its
    own length). The scalar path's lowering is unchanged by the vector
    extension: the branch resolves at trace time.
    """
    b, sq, h, d = q.shape
    n_kv = k.shape[2]
    qg = _group_heads(q, n_kv)  # [b, sq, n_kv, g, d]
    scale = d ** -0.5
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qg * scale, k,
                        preferred_element_type=jnp.float32)
    if jnp.ndim(q_offset) >= 1:
        q_pos = q_offset[:, None, None] + jnp.arange(sq)[None, :, None]
        kv_pos = jnp.arange(k.shape[1])[None, None, :]
        mask = q_pos >= kv_pos  # [b, sq, skv]
        logits = jnp.where(mask[:, None, None], logits, NEG_INF)
    else:
        q_pos = jnp.arange(sq)[:, None] + q_offset
        kv_pos = jnp.arange(k.shape[1])[None, :]
        mask = q_pos >= kv_pos  # causal
        logits = jnp.where(mask[None, None, None], logits, NEG_INF)
    probs = jnp.exp(logits - lax.stop_gradient(
        jnp.max(logits, axis=-1, keepdims=True)))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs.astype(v.dtype), v)
    return out.reshape(b, sq, h, d)


def decode_attention(q: jnp.ndarray, k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                     lengths: jnp.ndarray) -> jnp.ndarray:
    """Single-step attention against a (padded) KV cache.

    q: [b, 1, h, d]; caches: [b, max_len, n_kv, d]; lengths: [b] — number of
    valid cache entries per sequence (the new token's K/V already written).
    """
    b, _, h, d = q.shape
    n_kv = k_cache.shape[2]
    qg = _group_heads(q, n_kv)[:, 0]  # [b, n_kv, g, d]
    scale = d ** -0.5
    logits = jnp.einsum("bkgd,bskd->bkgs", qg * scale, k_cache,
                        preferred_element_type=jnp.float32)
    valid = jnp.arange(k_cache.shape[1])[None, :] < lengths[:, None]  # [b, s]
    logits = jnp.where(valid[:, None, None], logits, NEG_INF)
    logits = logits - jnp.max(logits, axis=-1, keepdims=True)
    probs = jnp.exp(logits)
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum("bkgs,bskd->bkgd", probs.astype(v_cache.dtype), v_cache)
    return out.reshape(b, 1, h, d)
