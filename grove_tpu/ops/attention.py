"""Attention ops: prefill (full causal) and decode (single-token vs cache).

Shapes follow the [batch, seq, heads, head_dim] convention throughout; GQA
is handled by repeating KV heads up to Q heads with a reshape-free einsum
grouping (no materialised repeat).

The prefill path is a plain jnp formulation — XLA fuses the softmax chain
and tiles the two matmuls onto the MXU; a pallas flash kernel can be slotted
in behind the same signature (see grove_tpu/ops/pallas/).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _group_heads(q: jnp.ndarray, n_kv: int) -> jnp.ndarray:
    """[b, s, h, d] -> [b, s, n_kv, group, d] view for GQA."""
    b, s, h, d = q.shape
    return q.reshape(b, s, n_kv, h // n_kv, d)


def causal_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     *, q_offset: jnp.ndarray | int = 0) -> jnp.ndarray:
    """Causal GQA attention for prefill.

    q: [b, sq, h, d]; k, v: [b, skv, n_kv, d]. ``q_offset`` is the absolute
    position of q[0] (for chunked prefill against a longer KV prefix).
    """
    b, sq, h, d = q.shape
    n_kv = k.shape[2]
    qg = _group_heads(q, n_kv)  # [b, sq, n_kv, g, d]
    scale = d ** -0.5
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qg * scale, k,
                        preferred_element_type=jnp.float32)
    q_pos = jnp.arange(sq)[:, None] + q_offset
    kv_pos = jnp.arange(k.shape[1])[None, :]
    mask = q_pos >= kv_pos  # causal
    logits = jnp.where(mask[None, None, None], logits, NEG_INF)
    probs = jnp.exp(logits - lax.stop_gradient(
        jnp.max(logits, axis=-1, keepdims=True)))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs.astype(v.dtype), v)
    return out.reshape(b, sq, h, d)


def decode_attention(q: jnp.ndarray, k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                     lengths: jnp.ndarray) -> jnp.ndarray:
    """Single-step attention against a (padded) KV cache.

    q: [b, 1, h, d]; caches: [b, max_len, n_kv, d]; lengths: [b] — number of
    valid cache entries per sequence (the new token's K/V already written).
    """
    b, _, h, d = q.shape
    n_kv = k_cache.shape[2]
    qg = _group_heads(q, n_kv)[:, 0]  # [b, n_kv, g, d]
    scale = d ** -0.5
    logits = jnp.einsum("bkgd,bskd->bkgs", qg * scale, k_cache,
                        preferred_element_type=jnp.float32)
    valid = jnp.arange(k_cache.shape[1])[None, :] < lengths[:, None]  # [b, s]
    logits = jnp.where(valid[:, None, None], logits, NEG_INF)
    logits = logits - jnp.max(logits, axis=-1, keepdims=True)
    probs = jnp.exp(logits)
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum("bkgs,bskd->bkgd", probs.astype(v_cache.dtype), v_cache)
    return out.reshape(b, 1, h, d)
