"""KV cache for incremental decoding.

A dense cache [layers, batch, max_len, n_kv, head_dim] with a per-lane
length vector. Static shapes throughout (jit-friendly); insertion is a
`dynamic_update_slice` along the sequence axis. The serving engine
allocates one cache per decode batch lane and recycles lanes (continuous
batching) — see grove_tpu/serving/engine.py.

Layer-level writes happen inside the model's `lax.scan` over layers (the
cache rows ride the scan as xs/ys), so the write helpers here operate on
single-lane rows and are shared by prefill and decode paths.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax


def write_row(row: jnp.ndarray, kv: jnp.ndarray, pos: jnp.ndarray | int) -> jnp.ndarray:
    """Write ``kv`` [s, n_kv, d] into one lane's cache row [max_len, n_kv, d]
    starting at ``pos``. NOTE: lax dynamic-update semantics clamp ``pos`` so
    the write never errors past max_len — callers must enforce capacity
    (see KVCache.has_room)."""
    return lax.dynamic_update_slice_in_dim(row, kv.astype(row.dtype), pos, axis=0)


class KVCache(NamedTuple):
    k: jnp.ndarray        # [layers, b, max_len, n_kv, d]
    v: jnp.ndarray        # [layers, b, max_len, n_kv, d]
    lengths: jnp.ndarray  # [b] int32 — valid entries per lane

    @classmethod
    def create(cls, n_layers: int, batch: int, max_len: int, n_kv: int,
               head_dim: int, dtype=jnp.bfloat16) -> "KVCache":
        shape = (n_layers, batch, max_len, n_kv, head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   lengths=jnp.zeros((batch,), jnp.int32))

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    def has_room(self, n_tokens: int = 1) -> jnp.ndarray:
        """[b] bool — lanes that can accept ``n_tokens`` more without the
        silent clamp in write_row corrupting the tail of the cache."""
        return self.lengths + n_tokens <= self.max_len
