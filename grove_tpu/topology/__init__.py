from grove_tpu.topology.fleet import FleetSpec, SliceSpec, create_fleet
from grove_tpu.topology.tpu import TPU_GENERATIONS, TpuGeneration, slice_hosts

__all__ = [
    "FleetSpec",
    "SliceSpec",
    "create_fleet",
    "TPU_GENERATIONS",
    "TpuGeneration",
    "slice_hosts",
]
