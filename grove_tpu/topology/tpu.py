"""TPU hardware model: generations, slice shapes, host counts.

The placement-relevant facts about TPU fleets (public GKE/Cloud TPU
topology semantics): a slice is one ICI-connected mesh described by a
topology string like "4x8" (v5e, 2D) or "4x4x8" (v5p, 3D torus); hosts
own 4 chips (v5e/v6e) or 4 chips across 2 trays (v5p: 4 chips/host); DCN
connects slices. Gang placement must treat the slice as atomic for ICI
collectives.
"""

from __future__ import annotations

import dataclasses
import math


@dataclasses.dataclass(frozen=True)
class TpuGeneration:
    name: str
    chips_per_host: int
    dims: int                  # topology dimensionality (2 or 3)
    hbm_gb_per_chip: int
    max_slice_chips: int


TPU_GENERATIONS: dict[str, TpuGeneration] = {
    "v5e": TpuGeneration("v5e", 4, 2, 16, 256),
    "v6e": TpuGeneration("v6e", 4, 2, 32, 256),
    "v5p": TpuGeneration("v5p", 4, 3, 95, 8960),
}


def parse_topology(topology: str) -> tuple[int, ...]:
    """'4x8' -> (4, 8); '4x4x8' -> (4, 4, 8)."""
    try:
        dims = tuple(int(p) for p in topology.lower().split("x"))
    except ValueError as e:
        raise ValueError(f"bad topology string {topology!r}") from e
    if not dims or any(d < 1 for d in dims):
        raise ValueError(f"bad topology string {topology!r}")
    return dims


def topology_chips(topology: str) -> int:
    return math.prod(parse_topology(topology))


def slice_hosts(generation: str, topology: str) -> int:
    """Number of hosts (TPU VMs / workers) in a slice."""
    gen = TPU_GENERATIONS[generation]
    chips = topology_chips(topology)
    if chips % gen.chips_per_host and chips >= gen.chips_per_host:
        raise ValueError(
            f"{generation} slice {topology}: {chips} chips not divisible by "
            f"{gen.chips_per_host} chips/host")
    return max(1, chips // gen.chips_per_host)
