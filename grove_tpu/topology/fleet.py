"""Fleet provisioning: materialise Node objects for TPU slices.

The KWOK-analog capacity source (SURVEY.md §4: fake nodes for control-
plane testing at scale): a FleetSpec describes pools of slices; create_fleet
writes the Node objects with the full TPU label schema so schedulers see
exactly what a GKE TPU node pool would expose. Real (subprocess-running)
nodes use the same labels with spec.fake=False.
"""

from __future__ import annotations

import dataclasses

from grove_tpu.api import Node, new_meta
from grove_tpu.api import constants as c
from grove_tpu.api.core import NodeSpec, NodeStatus
from grove_tpu.store.client import Client
from grove_tpu.topology.tpu import TPU_GENERATIONS, slice_hosts


@dataclasses.dataclass
class SliceSpec:
    generation: str = "v5e"
    topology: str = "4x4"        # ICI mesh shape, e.g. "4x8" = 32 chips
    count: int = 1               # how many such slices
    pool: str = "pool-0"
    superblock: str = ""         # defaults to pool


@dataclasses.dataclass
class FleetSpec:
    slices: list[SliceSpec] = dataclasses.field(default_factory=list)
    fake: bool = True


def node_name(slice_name: str, worker: int) -> str:
    return f"{slice_name}-w{worker}"


def build_node(generation: str, topology: str, slice_name: str, worker: int,
               pool: str = "pool-0", superblock: str = "",
               namespace: str = "default", fake: bool = True) -> Node:
    """One host's Node object (labels = the GKE TPU node-label contract).
    Shared by fleet creation and remote-agent self-registration."""
    gen = TPU_GENERATIONS[generation]
    name = node_name(slice_name, worker)
    return Node(
        meta=new_meta(name, namespace=namespace, labels={
            c.NODE_LABEL_TPU_ACCELERATOR: f"tpu-{generation}",
            c.NODE_LABEL_TPU_TOPOLOGY: topology,
            c.NODE_LABEL_SLICE: slice_name,
            c.NODE_LABEL_SLICE_WORKER: str(worker),
            c.NODE_LABEL_POOL: pool,
            c.NODE_LABEL_SUPERBLOCK: superblock or pool,
            c.NODE_LABEL_HOST: name,
        }),
        spec=NodeSpec(tpu_chips=gen.chips_per_host, fake=fake),
        status=NodeStatus(ready=True,
                          allocatable_chips=gen.chips_per_host),
    )


def create_fleet(client: Client, fleet: FleetSpec,
                 namespace: str = "default") -> list[Node]:
    """Create Node objects for every host of every slice in the fleet."""
    from grove_tpu.runtime.errors import AlreadyExistsError

    nodes: list[Node] = []
    slice_seq = 0
    for spec in fleet.slices:
        hosts = slice_hosts(spec.generation, spec.topology)
        for _ in range(spec.count):
            slice_name = f"{spec.pool}-slice-{slice_seq}"
            slice_seq += 1
            for w in range(hosts):
                node = build_node(
                    spec.generation, spec.topology, slice_name, w,
                    pool=spec.pool, superblock=spec.superblock,
                    namespace=namespace, fake=fleet.fake)
                try:
                    nodes.append(client.create(node))
                except AlreadyExistsError:
                    # Persistent-state reboot with the same fleet flag:
                    # the node survived the restart; keep it.
                    nodes.append(client.get(Node, node.meta.name,
                                            namespace))
    return nodes
