"""Pluggable scheduler-backend framework.

Role parity with reference internal/scheduler/types.go:35-115 (Backend /
TopologyAwareBackend / Registry): the operator talks to gang schedulers
only through this seam. Differences, TPU-first:

- Native backends (``gang``, ``simple``) ship their own placement loop as
  a runnable, because this framework is its own control plane — there is
  no external kube-scheduler to delegate to. The ``external`` backend
  preserves the delegate-out path (reference ``lpx``).
- Placement binds pods to TPU hosts honoring slice atomicity rather than
  emitting a foreign CRD.
"""

from __future__ import annotations

from typing import Any, Optional, Protocol, runtime_checkable

from grove_tpu.api.podcliqueset import PodCliqueSet
from grove_tpu.api.podgang import PodGang
from grove_tpu.api.core import Pod
from grove_tpu.store.client import Client


@runtime_checkable
class Backend(Protocol):
    """A scheduler integration."""

    name: str

    def init(self, client: Client, options: dict[str, str]) -> None:
        """Wire the backend to the control plane (called once at startup)."""
        ...

    def prepare_pod(self, pod: Pod, gang_name: str) -> None:
        """Stamp backend-specific fields onto a pod at build time
        (reference Backend.PreparePod)."""
        ...

    def sync_podgang(self, gang: PodGang) -> None:
        """Accept/translate a PodGang (reference Backend.SyncPodGang)."""
        ...

    def validate_pcs(self, pcs: PodCliqueSet) -> list[str]:
        """Backend-specific admission checks (reference
        Backend.ValidatePodCliqueSet). Returns problems; empty == ok."""
        ...

    def runnable(self) -> Optional[Any]:
        """The backend's placement loop (start()/stop()), if native."""
        ...


@runtime_checkable
class TopologyAware(Protocol):
    """Backends that consume ClusterTopology (reference types.go:59-93)."""

    def sync_topology(self, topology: Any) -> None: ...
    def check_topology_drift(self, topology: Any) -> bool: ...


class Registry:
    """Profile-name -> backend (reference types.go:96-115)."""

    def __init__(self, default: str):
        self._backends: dict[str, Backend] = {}
        self._default = default

    def register(self, profile: str, backend: Backend) -> None:
        self._backends[profile] = backend

    def get(self, profile: str | None = None) -> Backend:
        name = profile or self._default
        if name not in self._backends:
            raise KeyError(
                f"no scheduler profile {name!r}; have {sorted(self._backends)}")
        return self._backends[name]

    def profiles(self) -> list[str]:
        return sorted(self._backends)

    def backends(self) -> list[Backend]:
        seen: dict[int, Backend] = {}
        for b in self._backends.values():
            seen[id(b)] = b
        return list(seen.values())
