"""Placement explainability — the "why is my gang pending" layer.

PR 3's lifecycle tracing answers "why was this gang *slow*"; this
module answers "why is this gang *stuck*" — the kube-scheduler
per-plugin-failure-message analog for Grove's gang placement. The gang
scheduler calls the builders here on FAILED placement attempts only
(``GangBackend._place_initial`` / the straggler path), producing a
``PlacementDiagnosis`` that is

- persisted on ``PodGang.status.last_diagnosis`` (refresh-throttled so
  a stuck gang does not turn the 0.2s placement tick into a status
  write storm),
- copied into an ``Unschedulable`` condition reason,
- served raw at ``GET /debug/placement/<ns>/<name>`` and rendered by
  ``grovectl explain``.

Cost contract: nothing here runs when placement succeeds; candidate
domains are bounded to ``EXPLAIN_TOP_K``; ``GROVE_EXPLAIN=0`` disables
the whole layer (status stays untouched, exactly the pre-explain
shape).
"""

from __future__ import annotations

import os
import time

from grove_tpu.api.meta import is_condition_true
from grove_tpu.api.podgang import (
    DomainDiagnosis,
    PlacementDiagnosis,
    PreemptionDiagnosis,
    PodGang,
)
from grove_tpu.scheduler.placement import (
    HostView,
    PodRequest,
    classify_fit_failure,
)

EXPLAIN_ENV = "GROVE_EXPLAIN"
REFRESH_ENV = "GROVE_EXPLAIN_REFRESH"
# Candidate-domain bound: the operator needs the closest fits, not a
# 4000-domain dump on every stuck gang's status.
EXPLAIN_TOP_K = 8
# Minimum seconds between persisted diagnosis refreshes for an
# unchanged failure (the placement tick is 0.2s; re-writing status per
# tick would wake every watching controller for no new information).
DEFAULT_REFRESH_SECONDS = 5.0


def explain_enabled() -> bool:
    """Read per call (tests and incident mitigation flip it live)."""
    return os.environ.get(EXPLAIN_ENV, "1") != "0"


# Diagnoses last refreshed before this wall-clock instant bypass the
# refresh throttle once: a completed defrag migration changed the world
# every pending diagnosis describes, so the gauges and explain surfaces
# must re-judge it now, not after GROVE_EXPLAIN_REFRESH runs out.
_refresh_floor = 0.0


def note_defrag_completed(now: float | None = None) -> None:
    """Called by the defrag executor when a migration lands (or aborts
    after moving pods): forces the next merge_diagnosis of every stale
    diagnosis to refresh instead of returning the pre-defrag record."""
    global _refresh_floor
    _refresh_floor = time.time() if now is None else now


def refresh_seconds() -> float:
    try:
        return float(os.environ.get(REFRESH_ENV, DEFAULT_REFRESH_SECONDS))
    except ValueError:
        return DEFAULT_REFRESH_SECONDS


def _lost_capacity(nodes) -> tuple[list[str], int, int]:
    """Nodes currently withholding capacity (NotReady or cordoned) and
    the chips they hold — the node-loss half of "this fit yesterday".
    Returns (first-K names, total count, total chips): the name list is
    bounded for the persisted status block, the count and chips cover
    every lost node so the two never disagree."""
    lost_nodes: list[str] = []
    lost_chips = 0
    for node in nodes:
        if node.status.ready and not node.spec.unschedulable:
            continue
        lost_nodes.append(node.meta.name)
        lost_chips += max(node.status.allocatable_chips,
                          node.spec.tpu_chips)
    lost_nodes.sort()
    return lost_nodes[:EXPLAIN_TOP_K], len(lost_nodes), lost_chips


def build_gang_diagnosis(gang: PodGang, requests: list[PodRequest],
                         snap, level: str, required: bool,
                         spread: dict[str, float],
                         preemption: PreemptionDiagnosis | None,
                         now: float | None = None) -> PlacementDiagnosis:
    """Diagnose one failed gang-atomic placement attempt against the
    pass snapshot: per-candidate-domain verdicts (bounded to the top-K
    closest fits), the preemption outcome, and lost-node capacity.
    Failure path only — never called when a plan exists."""
    now = time.time() if now is None else now
    requested = sum(r.chips for r in requests)
    by_domain = snap.index.domains(level)
    indexed = by_domain is not None
    if by_domain is None:
        by_domain = {}
        for h in snap.hosts:
            by_domain.setdefault(
                h.name if level == "host" else h.domains.get(level, ""),
                []).append(h)
    # Rank candidates by free capacity (closest fit first), bound to
    # top-K, and only then pay for per-domain fit classification.
    ranked = sorted(
        ((snap.index.free_in(level, d) if indexed
          else sum(h.free_chips for h in hs), d, hs)
         for d, hs in by_domain.items()),
        key=lambda t: (-t[0], t[1]))
    entries: list[DomainDiagnosis] = []
    for free, domain, dhosts in ranked[:EXPLAIN_TOP_K]:
        total = sum(h.total_chips or h.free_chips for h in dhosts)
        if free < requested:
            verdict = "chip-shortfall"
            detail = f"{requested - free} chips short"
        else:
            verdict, detail = classify_fit_failure(requests, dhosts)
        entries.append(DomainDiagnosis(
            domain=domain, level=level, free_chips=free,
            total_chips=total, verdict=verdict, detail=detail,
            spread_penalty=spread.get(domain, 0.0)))
    if entries:
        entries[0].closest = True

    lost_nodes, lost_total, lost_chips = _lost_capacity(snap.nodes)
    cluster_free = sum(h.free_chips for h in snap.hosts)

    if preemption is not None and \
            preemption.verdict == "victims-insufficient":
        reason = "PreemptionRejected"
    elif not entries or all(e.verdict == "chip-shortfall"
                            for e in entries):
        # Every candidate is short on chips: if the cluster as a whole
        # could hold the gang, the pack constraint is what blocks it.
        reason = ("TopologyPruned"
                  if required and cluster_free >= requested
                  else "ChipShortfall")
    elif all(e.verdict == "selector-mismatch" for e in entries):
        reason = "SelectorMismatch"
    else:
        reason = "Fragmented"

    closest = entries[0] if entries else None
    msg = (f"no {level} domain fits {len(requests)} pods "
           f"({requested} chips)")
    if closest is not None:
        msg += (f"; closest {level} {closest.domain!r} has "
                f"{closest.free_chips} free chips ({closest.verdict}"
                + (f": {closest.detail}" if closest.detail else "") + ")")
    if preemption is not None and preemption.verdict != "preempted":
        msg += f"; preemption {preemption.verdict}"
        if preemption.detail:
            msg += f" ({preemption.detail})"
    if lost_nodes:
        msg += (f"; {lost_total} node(s) NotReady/cordoned "
                f"withholding {lost_chips} chips (node loss)")

    return PlacementDiagnosis(
        reason=reason, message=msg, pods=len(requests),
        requested_chips=requested, pack_level=level, required=required,
        domains=entries, domains_total=len(by_domain),
        preemption=preemption, lost_nodes=lost_nodes,
        lost_nodes_total=lost_total, lost_chips=lost_chips,
        last_attempt_time=now)


def build_straggler_diagnosis(gang: PodGang, unplaced: list,
                              level: str, anchor: str,
                              snap=None,
                              now: float | None = None
                              ) -> PlacementDiagnosis:
    """Diagnose late pods (gang scale-up / recreated pods) that could
    not rejoin their bound siblings: the anchor domain every required
    pack constraint pins them to lacks room. ``unplaced`` is a list of
    (pod, pool) pairs — pools can differ per pod (group constraints,
    selectors), so the reported numbers come from the TIGHTEST pool (a
    roomier sibling pool must not make a stuck pod look placeable)."""
    now = time.time() if now is None else now
    pods = [p for p, _ in unplaced]
    requested = sum(p.spec.tpu_chips for p in pods)
    pod, pool = min(unplaced,
                    key=lambda pp: sum(h.free_chips for h in pp[1]))
    free = sum(h.free_chips for h in pool)
    total = sum(h.total_chips or h.free_chips for h in pool)
    names = ", ".join(sorted(p.meta.name for p in pods)[:4])
    entry = DomainDiagnosis(
        domain=anchor, level=level, free_chips=free, total_chips=total,
        verdict=("chip-shortfall" if free < pod.spec.tpu_chips
                 else "fragmented"),
        detail=f"pod {pod.meta.name}'s anchor pool: {len(pool)} "
               f"host(s), {free} free chips for its "
               f"{pod.spec.tpu_chips}-chip request", closest=True)
    lost_nodes, lost_total, lost_chips = ([], 0, 0) if snap is None \
        else _lost_capacity(snap.nodes)
    msg = (f"{len(pods)} late pod(s) ({names}) cannot rejoin the "
           f"gang: anchor {level} {anchor!r} has {free} free chips, "
           f"{requested} needed")
    if lost_nodes:
        msg += (f"; {lost_total} node(s) NotReady/cordoned "
                f"withholding {lost_chips} chips (node loss)")
    return PlacementDiagnosis(
        reason="StragglerUnplaced", message=msg, pods=len(pods),
        requested_chips=requested, pack_level=level, required=True,
        domains=[entry], domains_total=1, lost_nodes=lost_nodes,
        lost_nodes_total=lost_total, lost_chips=lost_chips,
        last_attempt_time=now)


def merge_diagnosis(prev: PlacementDiagnosis | None,
                    fresh: PlacementDiagnosis,
                    now: float | None = None) -> PlacementDiagnosis:
    """Fold a fresh attempt into the persisted history: carry attempt
    count and first-failure time forward, and — when nothing material
    changed inside the refresh window — return ``prev`` unchanged so
    the status write is a suppressed no-op (the store's byte-identical
    guard) instead of a per-tick rv bump."""
    now = time.time() if now is None else now
    if prev is not None:
        unchanged = (prev.reason == fresh.reason
                     and prev.message == fresh.message)
        if unchanged and now - prev.last_attempt_time < refresh_seconds() \
                and prev.last_attempt_time >= _refresh_floor:
            return prev
        fresh.attempts = prev.attempts + 1
        fresh.first_failure_time = prev.first_failure_time or now
    else:
        fresh.attempts = 1
        fresh.first_failure_time = now
    fresh.last_attempt_time = now
    return fresh


# ---- wire payload + CLI rendering (shared by server, clients, CLI) ----


def placement_payload(gang: PodGang) -> dict:
    """The raw-diagnosis wire shape served by GET /debug/placement and
    both clients' ``debug_placement`` — one shape everywhere."""
    from grove_tpu.api import constants as c
    from grove_tpu.api.serde import to_dict
    return {
        "kind": "PodGang",
        "name": gang.meta.name,
        "namespace": gang.meta.namespace,
        "phase": gang.status.phase.value,
        "scheduled": is_condition_true(gang.status.conditions,
                                       c.COND_SCHEDULED),
        "assigned_slice": gang.status.assigned_slice,
        "reuse_reservation_ref": gang.status.reuse_reservation_ref,
        "conditions": [to_dict(cd) for cd in gang.status.conditions],
        "diagnosis": (to_dict(gang.status.last_diagnosis)
                      if gang.status.last_diagnosis is not None else None),
    }


def payload_from_obj(obj: dict) -> dict:
    """``placement_payload`` shape from a plain ``/api/PodGang`` object
    dict (the PCS aggregation path lists gangs once instead of one
    debug round trip per member)."""
    from grove_tpu.api import constants as c
    st = obj.get("status", {}) or {}
    scheduled = any(cd.get("type") == c.COND_SCHEDULED
                    and cd.get("status") == "True"
                    for cd in st.get("conditions") or [])
    return {
        "kind": "PodGang",
        "name": (obj.get("meta", {}) or {}).get("name", ""),
        "namespace": (obj.get("meta", {}) or {}).get("namespace",
                                                     "default"),
        "phase": st.get("phase", ""),
        "scheduled": scheduled,
        "assigned_slice": st.get("assigned_slice", ""),
        "reuse_reservation_ref": st.get("reuse_reservation_ref", ""),
        "conditions": st.get("conditions") or [],
        "diagnosis": st.get("last_diagnosis"),
    }


def render_explain(payload: dict, now: float | None = None) -> list[str]:
    """Human-readable reason tree for one gang's placement payload —
    what ``grovectl explain`` prints. Works on the wire dict so the CLI
    renders identically from the debug endpoint and from listed
    objects."""
    now = time.time() if now is None else now
    name = f"PodGang/{payload.get('name', '')}"
    diag = payload.get("diagnosis")
    hold = payload.get("reuse_reservation_ref", "")
    hold_line = (
        f"  reservation: holds {hold!r} — a defrag migration target or "
        "roll-safe slot hold; the gang is pinned to (and admitted onto) "
        "the reserved slice until the hold releases" if hold else "")
    lines: list[str] = []
    if diag is None:
        where = payload.get("assigned_slice") or "multiple domains"
        state = ("scheduled onto " + where if payload.get("scheduled")
                 else f"phase {payload.get('phase', '?')}, no placement "
                      "diagnosis recorded")
        if hold and not payload.get("scheduled"):
            state = (f"phase {payload.get('phase', '?')}, relanding onto "
                     f"reservation {hold!r}")
        lines.append(f"{name}: {state}")
        if hold_line:
            lines.append(hold_line)
        return lines
    pending = max(0.0, now - diag.get("first_failure_time", now))
    # A diagnosis can coexist with Scheduled=True (min-floor placed,
    # surplus stragglers stuck): say both, never hide the reason tree.
    state = ("SCHEDULED AT FLOOR" if payload.get("scheduled")
             else "UNSCHEDULABLE")
    lines.append(
        f"{name}: {state} — {diag.get('reason', '?')} "
        f"(attempt {diag.get('attempts', 0)}, "
        f"pending {pending:.0f}s)")
    lines.append(f"  {diag.get('message', '')}")
    lines.append(
        f"  requested: {diag.get('requested_chips', 0)} chips across "
        f"{diag.get('pods', 0)} pods "
        f"(pack {diag.get('pack_level', '?')}, "
        f"{'required' if diag.get('required', True) else 'preferred'})")
    if hold_line:
        # Pending BECAUSE of a hold is a different story than a bare
        # capacity verdict: say the gang is awaiting its reserved slice.
        lines.append(hold_line)
    domains = diag.get("domains") or []
    if domains:
        total = diag.get("domains_total", len(domains))
        bound = (f"top {len(domains)} of {total}" if total > len(domains)
                 else str(len(domains)))
        lines.append(f"  candidate domains ({bound}; * = closest fit):")
        for d in domains:
            star = "*" if d.get("closest") else " "
            pen = (f", spread penalty {d.get('spread_penalty', 0.0):.1f}"
                   if d.get("spread_penalty") else "")
            detail = f" ({d['detail']})" if d.get("detail") else ""
            lines.append(
                f"  {star} {d.get('level', '?')} {d.get('domain', '?')!r}: "
                f"{d.get('free_chips', 0)}/{d.get('total_chips', 0)} "
                f"chips free — {d.get('verdict', '?')}{detail}{pen}")
    pre = diag.get("preemption")
    if pre:
        detail = f" — {pre['detail']}" if pre.get("detail") else ""
        lines.append(
            f"  preemption: {pre.get('verdict', '?')}"
            f" ({pre.get('victims_considered', 0)} victim(s), "
            f"{pre.get('victim_chips', 0)} chips){detail}")
    if diag.get("lost_nodes"):
        shown = diag["lost_nodes"]
        total = diag.get("lost_nodes_total", len(shown))
        more = f" (+{total - len(shown)} more)" if total > len(shown) \
            else ""
        lines.append(
            f"  node loss: {', '.join(shown)}{more} "
            f"withholding {diag.get('lost_chips', 0)} chips")
    return lines
