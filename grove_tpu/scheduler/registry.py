"""Build the backend registry from operator config profiles.

Parity with reference internal/scheduler/registry/registry.go: profiles
from OperatorConfiguration become named backends; each backend's Init is
called once with its options; the default profile resolves lookups with
no explicit scheduler name.
"""

from __future__ import annotations

from grove_tpu.api.config import OperatorConfiguration
from grove_tpu.scheduler.backends import (
    ExternalBackend,
    GangBackend,
    SimpleBackend,
)
from grove_tpu.scheduler.framework import Registry
from grove_tpu.store.client import Client

_FACTORIES = {
    "gang": GangBackend,
    "simple": SimpleBackend,
    "external": ExternalBackend,
}


def build_registry(config: OperatorConfiguration, client: Client) -> Registry:
    registry = Registry(default=config.default_scheduler_profile)
    for profile in config.scheduler_profiles:
        factory = _FACTORIES.get(profile.backend)
        if factory is None:
            raise ValueError(
                f"scheduler profile {profile.name!r}: unknown backend "
                f"{profile.backend!r}; have {sorted(_FACTORIES)}")
        backend = factory()
        backend.init(client, dict(profile.options))
        registry.register(profile.name, backend)
    return registry
