"""Pure placement planning — the scheduler's computational core.

Separated from the control loop so it is unit-testable and portable (the
hot path is plain data in/out; a C++ drop-in can replace plan_* without
touching the loop). Implements TPU slice-atomic gang placement:

- ``pack_level == "slice"`` + required: every pod of the gang lands inside
  ONE ICI slice (the reference's NVLink-domain pack made atomic).
- preferred packing: try slice, then pool, then anywhere.
- Reuse: a gang replacing another (rolling update) prefers its old slice
  (reference ReuseReservationRef, podgang.go:65-71).
- Spread: sibling gangs of one PodCliqueSet prefer distinct domains at the
  spread level (multislice DP over DCN).
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict

from grove_tpu.api.constants import LABEL_RESERVATION as _LABEL_RESERVATION


@dataclasses.dataclass
class HostView:
    """Free capacity on one TPU host, with its topology domains.

    ``domains`` maps ClusterTopology level names (pool / superblock /
    slice / host, or custom hierarchies) to this host's domain value —
    resolved from node labels by the backend using its synced topology.
    """

    name: str
    free_chips: int
    domains: dict[str, str] = dataclasses.field(default_factory=dict)
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    # Full allocatable capacity (free + in use): lets planners judge
    # whether a domain could EVER hold a workload, not just whether it
    # can right now (min-floor anchoring must avoid undersized domains).
    total_chips: int = 0

    @property
    def slice_name(self) -> str:
        return self.domains.get("slice", "")


def _selector_matches(pod: "PodRequest", host: HostView) -> bool:
    # Reserved capacity is exclusive (taint-like): a host carrying a
    # reservation label admits ONLY pods that select that reservation —
    # otherwise general workloads would squat on slices a PCS paid to
    # hold (api/reservation.py). Constant hoisted: this runs per
    # pod-host pair in the planners' eligibility loops.
    held_by = host.labels.get(_LABEL_RESERVATION)
    if held_by and pod.node_selector.get(_LABEL_RESERVATION) != held_by:
        return False
    return all(host.labels.get(k) == v for k, v in pod.node_selector.items())


@dataclasses.dataclass
class PodRequest:
    name: str
    chips: int
    node_selector: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class PlacementPlan:
    assignments: dict[str, str]      # pod name -> host name
    slice_name: str                  # "" when the plan spans slices
    score: float                     # higher is better (bin-pack tightness)


def _domain_of(host: HostView, level: str) -> str:
    if level == "host":
        return host.name
    return host.domains.get(level, "")


class DomainIndex:
    """Per-level host indexes: level -> domain value -> hosts, with a
    running free-chip total per domain.

    Built once per placement snapshot and mutated in place as binds
    land (``deduct``), so the planners can (a) skip the per-call
    group-hosts-by-domain scan and (b) prune candidate domains whose
    total free capacity cannot hold the gang — without rescanning every
    host per pod. The index holds REFERENCES to the caller's HostViews:
    a ``deduct`` updates both the host and every level's running total,
    keeping index and views coherent by construction.
    """

    def __init__(self, hosts: list[HostView],
                 levels: "list[str] | tuple[str, ...]" = ()) -> None:
        self.levels = list(dict.fromkeys(levels)) or ["slice"]
        if "host" not in self.levels:
            self.levels.append("host")
        self._hosts_by: dict[str, dict[str, list[HostView]]] = {
            lvl: defaultdict(list) for lvl in self.levels}
        self._free_by: dict[str, dict[str, int]] = {
            lvl: defaultdict(int) for lvl in self.levels}
        for h in hosts:
            self.add(h)

    def add(self, host: HostView) -> None:
        for lvl in self.levels:
            d = _domain_of(host, lvl)
            self._hosts_by[lvl][d].append(host)
            self._free_by[lvl][d] += host.free_chips

    def deduct(self, host: HostView, chips: int) -> None:
        """Account a bind: the host loses ``chips`` and every enclosing
        domain's free total drops with it."""
        host.free_chips -= chips
        for lvl in self.levels:
            self._free_by[lvl][_domain_of(host, lvl)] -= chips

    def domains(self, level: str) -> dict[str, list[HostView]] | None:
        """The precomputed domain -> hosts map for ``level`` (None when
        the level is not indexed — callers fall back to a scan)."""
        return self._hosts_by.get(level)

    def hosts_in(self, level: str, domain: str) -> list[HostView]:
        by = self._hosts_by.get(level)
        return list(by.get(domain, ())) if by is not None else []

    def free_in(self, level: str, domain: str) -> int:
        by = self._free_by.get(level)
        return by.get(domain, 0) if by is not None else 0


def _fit_in_hosts(pods: list[PodRequest], hosts: list[HostView]
                  ) -> dict[str, str] | None:
    """First-fit-decreasing of pods onto hosts. Returns assignment or None."""
    free = {h.name: h.free_chips for h in hosts}
    order = sorted(hosts, key=lambda h: -h.free_chips)
    assignment: dict[str, str] = {}
    for pod in sorted(pods, key=lambda p: -p.chips):
        placed = False
        for h in order:
            if free[h.name] >= pod.chips and _selector_matches(pod, h):
                assignment[pod.name] = h.name
                free[h.name] -= pod.chips
                placed = True
                break
        if not placed:
            return None
    return assignment


def classify_fit_failure(pods: list[PodRequest], hosts: list[HostView]
                         ) -> tuple[str, str]:
    """Why no assignment exists for ``pods`` on ``hosts`` even though
    the total free chips may cover the request — the explainability
    companion to ``_fit_in_hosts`` (failure paths only; never on the
    placement hot path). Returns (verdict, detail):

    - ``selector-mismatch``: some pod's node_selector (or a reservation
      fence) excludes every host;
    - ``fragmented``: every host a pod may land on lacks a free block
      its size, or the pods fit individually but not together.
    """
    for pod in sorted(pods, key=lambda p: -p.chips):
        eligible = [h for h in hosts if _selector_matches(pod, h)]
        if not eligible:
            sel = ",".join(f"{k}={v}" for k, v in
                           sorted(pod.node_selector.items())) or "<none>"
            return ("selector-mismatch",
                    f"pod {pod.name} matches no host (selector {sel})")
        biggest = max(h.free_chips for h in eligible)
        if biggest < pod.chips:
            return ("fragmented",
                    f"pod {pod.name} needs {pod.chips} chips but the "
                    f"largest free block is {biggest}")
    return ("fragmented", "pods fit individually but not together")


def plan_gang(pods: list[PodRequest], hosts: list[HostView],
              pack_level: str = "slice", required: bool = True,
              prefer_slice: str = "",
              spread_penalty: dict[str, float] | None = None,
              domain_index: DomainIndex | None = None
              ) -> PlacementPlan | None:
    """Plan placement for all ``pods`` together (gang semantics).

    ``spread_penalty`` maps domain value (at the caller's spread level,
    pre-resolved to slice names) -> penalty subtracted from the score.

    ``domain_index`` (optional) is a DomainIndex built over exactly
    ``hosts``: when it covers ``pack_level`` the per-call domain
    grouping scan is skipped. Decisions are identical with or without
    it.

    Dispatches to the native C++ core (grove_tpu/native/placement.cpp)
    when available; this Python body is the reference semantics and the
    fallback. Disable native with GROVE_NATIVE_PLACEMENT=0.
    """
    if not pods:
        return PlacementPlan({}, "", 0.0)
    level = pack_level or "slice"
    used_chips = sum(p.chips for p in pods)
    by_domain, hosts = _prune_candidates(domain_index, level, required,
                                         used_chips, hosts)
    if not hosts:
        return None
    import os
    if os.environ.get("GROVE_NATIVE_PLACEMENT", "1") != "0":
        from grove_tpu.native.loader import native_plan_gang
        result = native_plan_gang(pods, hosts, pack_level, required,
                                  prefer_slice, spread_penalty or {})
        if result is not NotImplemented:
            return result
    spread_penalty = spread_penalty or {}

    if by_domain is None:
        by_domain = defaultdict(list)
        for h in hosts:
            by_domain[_domain_of(h, level)].append(h)

    return _best_domain_plan(by_domain, hosts, _fit_in_hosts_of(pods),
                             used_chips, level, required,
                             prefer_slice, spread_penalty)


def _fit_in_hosts_of(pods: list[PodRequest]):
    return lambda domain_hosts: _fit_in_hosts(pods, domain_hosts)


def _prune_candidates(domain_index: DomainIndex | None, level: str,
                      required: bool, used_chips: int,
                      hosts: list[HostView]
                      ) -> tuple[dict[str, list[HostView]] | None,
                                 list[HostView]]:
    """Candidate pruning via the index's free totals, shared by the
    flat and grouped planners: under a REQUIRED pack every feasible
    plan lives inside one domain, so domains whose total free chips
    fall short of the gang can be dropped before the planner (native
    or Python) scans their hosts per pod. Decision-identical — only
    certainly-infeasible domains are removed. Returns (by_domain,
    hosts); by_domain is None when the index doesn't cover ``level``
    (callers fall back to a scan), hosts shrinks only when pruning
    applied (an empty result means no domain can fit the gang)."""
    if domain_index is None:
        return None, hosts
    by_domain = domain_index.domains(level)
    if by_domain is None or not required:
        return by_domain, hosts
    by_domain = {d: hs for d, hs in by_domain.items()
                 if domain_index.free_in(level, d) >= used_chips}
    return by_domain, [h for hs in by_domain.values() for h in hs]


def _best_domain_plan(by_domain, all_hosts, fit_fn, used_chips, level,
                      required, prefer_slice, spread_penalty
                      ) -> PlacementPlan | None:
    """Score every candidate domain with ``fit_fn`` and pick the best;
    relax across all hosts when the pack is only preferred. Shared by the
    flat and per-group planners so scoring semantics cannot diverge."""
    candidates: list[PlacementPlan] = []
    for domain, domain_hosts in by_domain.items():
        total_free = sum(h.free_chips for h in domain_hosts)
        if total_free < used_chips:
            # Capacity prune: no assignment can exist when the domain's
            # total free chips fall short of the gang's demand — skip
            # the per-pod fitting entirely. Decision-identical (fit_fn
            # would return None) but O(hosts) instead of O(pods*hosts).
            continue
        assignment = fit_fn(domain_hosts)
        if assignment is None:
            continue
        tightness = used_chips / total_free if total_free else 1.0
        score = tightness - spread_penalty.get(domain, 0.0)
        if prefer_slice and domain == prefer_slice:
            score += 10.0   # reuse dominates
        slice_name = domain if level == "slice" else ""
        candidates.append(PlacementPlan(assignment, slice_name, score))

    if candidates:
        return max(candidates, key=lambda p: p.score)
    if required:
        return None
    # Preferred packing failed -> relax across all hosts.
    assignment = fit_fn(all_hosts)
    if assignment is None:
        return None
    return PlacementPlan(assignment, "", -1.0)


@dataclasses.dataclass
class GroupRequest:
    """A PodGroup with its own (stricter) pack constraint."""

    pods: list[PodRequest]
    pack_level: str = ""          # "" = no group-level constraint
    required: bool = True         # False = preferred (relaxes on failure)


def plan_gang_grouped(groups: list[GroupRequest], hosts: list[HostView],
                      pack_level: str = "slice", required: bool = True,
                      prefer_slice: str = "",
                      spread_penalty: dict[str, float] | None = None,
                      domain_index: DomainIndex | None = None
                      ) -> PlacementPlan | None:
    """Gang planning with per-group pack constraints (reference
    PodGroup.TopologyConstraint, scheduler api podgang.go:99-117).

    The gang-level constraint picks the enclosing domain as in plan_gang;
    inside it, each group with its own stricter level is packed into ONE
    sub-domain of that level (e.g. a gang packed per pool with each
    group slice-resident). Groups without constraints fill remaining
    capacity anywhere in the gang domain.
    """
    all_pods = [p for g in groups for p in g.pods]
    if not any(g.pack_level for g in groups):
        return plan_gang(all_pods, hosts, pack_level=pack_level,
                         required=required, prefer_slice=prefer_slice,
                         spread_penalty=spread_penalty,
                         domain_index=domain_index)
    level = pack_level or "slice"
    used_chips = sum(p.chips for p in all_pods)
    by_domain, hosts = _prune_candidates(domain_index, level, required,
                                         used_chips, hosts)
    if not hosts:
        return None
    import os
    if os.environ.get("GROVE_NATIVE_PLACEMENT", "1") != "0":
        from grove_tpu.native.loader import native_plan_gang_grouped
        result = native_plan_gang_grouped(groups, hosts, pack_level,
                                          required, prefer_slice,
                                          spread_penalty or {})
        if result is not NotImplemented:
            return result
    spread_penalty = spread_penalty or {}
    if by_domain is None:
        by_domain = defaultdict(list)
        for h in hosts:
            by_domain[_domain_of(h, level)].append(h)

    def plan_in_domain(domain_hosts: list[HostView]) -> dict[str, str] | None:
        free = {h.name: h.free_chips for h in domain_hosts}
        assignment: dict[str, str] = {}

        def commit(sub: dict[str, str], pods: list[PodRequest]) -> None:
            chips = {p.name: p.chips for p in pods}
            for pn, hn in sub.items():
                assignment[pn] = hn
                free[hn] -= chips[pn]

        def views() -> list[HostView]:
            return [dataclasses.replace(h, free_chips=free[h.name])
                    for h in domain_hosts]

        # Constrained groups first (hardest), largest demand first.
        constrained = sorted((g for g in groups if g.pack_level),
                             key=lambda g: -sum(p.chips for p in g.pods))
        for g in constrained:
            sub_plan = plan_gang(g.pods, views(), pack_level=g.pack_level,
                                 required=g.required)
            if sub_plan is None:
                return None
            commit(sub_plan.assignments, g.pods)
        rest = [p for g in groups if not g.pack_level for p in g.pods]
        if rest:
            sub = _fit_in_hosts(rest, views())
            if sub is None:
                return None
            commit(sub, rest)
        return assignment

    return _best_domain_plan(by_domain, hosts, plan_in_domain,
                             sum(p.chips for p in all_pods), level,
                             required, prefer_slice, spread_penalty)


def plan_single(pod: PodRequest, hosts: list[HostView],
                prefer_slice: str = "") -> str | None:
    """Place one pod (simple backend / gang stragglers). Returns host name.

    Prefers the given slice (late pods of a gang co-locate), then tightest
    fit.
    """
    best: tuple[float, str] | None = None
    for h in hosts:
        if h.free_chips < pod.chips or not _selector_matches(pod, h):
            continue
        score = -h.free_chips + (1000.0 if h.slice_name == prefer_slice else 0.0)
        if best is None or score > best[0]:
            best = (score, h.name)
    return best[1] if best else None
