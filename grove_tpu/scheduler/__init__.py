from grove_tpu.scheduler.framework import Backend, Registry, TopologyAware
from grove_tpu.scheduler.registry import build_registry

__all__ = ["Backend", "Registry", "TopologyAware", "build_registry"]
