"""Scheduler backends: gang (native, slice-atomic), simple, external.

Backend set parity with reference internal/scheduler/{kai,volcano,kube,lpx}
re-based on TPU-native placement:

- ``gang``    — the KAI/Volcano role: consumes PodGangs natively and
                gang-places onto TPU slices (atomic ICI placement, reuse
                hints, DCN spread). Ships the placement loop.
- ``simple``  — the kube role: no gang semantics, first-fit single pods
                (gating still guarantees all-pods-exist before placement).
- ``external``— the lpx role: stamps scheduler_name and delegates
                placement to an out-of-process scheduler; rejects Grove
                topology constraints it cannot honor.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from typing import Optional

from grove_tpu.api import Node, Pod, PodGang, constants as c, namegen
from grove_tpu.api.meta import (
    Condition,
    is_condition_true,
    set_condition,
    trace_id_of,
)
from grove_tpu.api.podcliqueset import PodCliqueSet
from grove_tpu.api.podgang import PodGangPhase, PreemptionDiagnosis
from grove_tpu.api.serde import clone
from grove_tpu.scheduler.explain import (
    build_gang_diagnosis,
    build_straggler_diagnosis,
    explain_enabled,
    merge_diagnosis,
)
from grove_tpu.runtime.errors import ConflictError, NotFoundError
from grove_tpu.runtime.logger import get_logger
from grove_tpu.runtime.trace import GLOBAL_TRACER
from grove_tpu.scheduler.placement import (
    DomainIndex,
    GroupRequest,
    HostView,
    PodRequest,
    plan_gang,
    plan_gang_grouped,
    plan_single,
)
from grove_tpu.store.client import Client


from grove_tpu.api.clustertopology import DEFAULT_TPU_LEVELS

DEFAULT_LEVEL_LABELS: dict[str, str] = {
    lvl.domain: lvl.node_label for lvl in DEFAULT_TPU_LEVELS}


def _host_views_from(pods: list[Pod], nodes: list[Node],
                     level_labels: dict[str, str]) -> list[HostView]:
    """HostViews from already-listed pods+nodes (shared by the snapshot
    and the plain build_host_views read)."""
    used: dict[str, int] = defaultdict(int)
    for pod in pods:
        if pod.status.node_name and pod.status.phase.value in ("Pending", "Running"):
            used[pod.status.node_name] += pod.spec.tpu_chips
    views = []
    for node in nodes:
        if not node.status.ready or node.spec.unschedulable:
            continue
        labels = node.meta.labels
        views.append(HostView(
            name=node.meta.name,
            free_chips=node.status.allocatable_chips - used[node.meta.name],
            domains={domain: labels.get(label, "")
                     for domain, label in level_labels.items()},
            labels=dict(labels),
            total_chips=node.status.allocatable_chips,
        ))
    return views


def build_host_views(client: Client, namespace: str | None = None,
                     level_labels: dict[str, str] | None = None
                     ) -> list[HostView]:
    """Snapshot free capacity per ready TPU host, resolving topology
    domains from node labels via the (possibly CT-synced) level map."""
    level_labels = level_labels or DEFAULT_LEVEL_LABELS
    return _host_views_from(client.list(Pod, namespace),
                            client.list(Node, namespace), level_labels)


def _incremental_enabled() -> bool:
    return os.environ.get("GROVE_SCHED_INCREMENTAL", "1") != "0"


class PlacementSnapshot:
    """One placement pass's world view — built once, mutated in place.

    Replaces the naive pass shape (full ``list(Pod)`` + ``list(Node)``
    rebuilt after every placed gang, plus a per-gang selector list) with
    one snapshot per pass:

    - pods and nodes come from the store's shared-clone snapshot path
      (``Client.list_snapshot``): no per-reader ``pickle.loads``;
    - a gang-name -> pods index (one scan over LABEL_PODGANG_NAME)
      replaces every per-gang selector list;
    - a DomainIndex (level -> domain -> hosts with free-chip totals)
      lets the planners prune candidate domains without rescanning
      every host per pod.

    After a successful bind the snapshot is mutated in place — chips
    deducted from the assigned hosts, bound pods swapped into the gang
    index — instead of re-listing the store. Every write the scheduler
    itself performs is counted (``note_own_writes``); after each placed
    gang the pass compares ``client.current_rv()`` against the
    snapshot's rv + its own write count and falls back to a full
    rebuild only when OUTSIDE writers moved the world. The rebuild is
    itself cheap: unchanged objects come straight from the store's
    snapshot cache.

    Read-only contract: pods/nodes here may be shared with other store
    readers — never mutate them (the bind path clones before writing).

    ``incremental=False`` reproduces the pre-snapshot cost shape
    (per-gang selector lists, full re-list after every placed gang) for
    apples-to-apples benchmarking — tools/bench_sched.py and the
    GROVE_SCHED_INCREMENTAL=0 escape hatch.
    """

    def __init__(self, client: Client, namespace: str | None,
                 level_labels: dict[str, str],
                 incremental: bool | None = None) -> None:
        self.client = client
        self.namespace = namespace
        self.level_labels = dict(level_labels)
        self.incremental = (_incremental_enabled()
                            if incremental is None else incremental)
        self.rebuilds = 0
        self._own_writes = 0
        self.rv = -1
        # Pass-lifetime gang index (index_gangs): owned by the pass,
        # NOT reset by _build — a mid-pass rebuild refreshes pods and
        # hosts, but the pass keeps iterating (and mutating) the gang
        # objects it listed at pass start, and spread penalties must
        # keep seeing them.
        self._gangs_by_pcs: dict[tuple[str, str], list[PodGang]] = {}
        self._build()

    # ---- build / freshness ----

    def _build(self) -> None:
        client = self.client
        if self.incremental and hasattr(client, "list_snapshot"):
            # rv is sampled under the same lock as the Pod refs: any
            # write after it — including one racing the Node list below
            # — shows up as a version skew and triggers a rebuild, so
            # the check is conservative, never blind.
            self.rv, pods = client.list_snapshot(Pod, self.namespace)
            _, nodes = client.list_snapshot(Node, self.namespace)
        else:
            # Clients without the shared-clone path (e.g. a wire
            # HttpClient) still get rv-based freshness when they expose
            # current_rv: sampled BEFORE the lists, so any interleaved
            # write shows as a skew and forces a rebuild (conservative).
            rv = (client.current_rv()
                  if self.incremental and hasattr(client, "current_rv")
                  else -1)
            pods = client.list(Pod, self.namespace)
            nodes = client.list(Node, self.namespace)
            self.rv = rv
        self._own_writes = 0
        self.pods = pods
        self.nodes = nodes
        self.hosts = _host_views_from(pods, nodes, self.level_labels)
        self.host_by_name = {h.name: h for h in self.hosts}
        self.index = DomainIndex(self.hosts,
                                 list(self.level_labels) + ["host"])
        self._by_gang: dict[tuple[str, str], dict[str, Pod]] = \
            defaultdict(dict)
        for pod in pods:
            gname = pod.meta.labels.get(c.LABEL_PODGANG_NAME)
            if gname:
                self._by_gang[(pod.meta.namespace, gname)][
                    pod.meta.name] = pod

    def index_gangs(self, gangs: list[PodGang]) -> None:
        """Index the pass's gang list by PCS label (spread penalties
        consult siblings per gang; one scan replaces G selector lists).
        The listed gang objects are the SAME objects the pass mutates as
        it places, so in-pass placements are visible to later penalties
        exactly as the per-gang re-list used to see them."""
        by_pcs: dict[tuple[str, str], list[PodGang]] = defaultdict(list)
        for g in gangs:
            pcs = g.meta.labels.get(c.LABEL_PCS_NAME, "")
            if pcs:
                by_pcs[(g.meta.namespace, pcs)].append(g)
        self._gangs_by_pcs = by_pcs

    def pcs_siblings(self, namespace: str, pcs: str) -> list[PodGang]:
        if not self.incremental:
            return self.client.list(PodGang, namespace,
                                    selector={c.LABEL_PCS_NAME: pcs})
        return self._gangs_by_pcs.get((namespace, pcs), [])

    def gang_pods(self, gang: PodGang) -> list[Pod]:
        """All existing pods labeled for ``gang`` (read-only objects)."""
        if not self.incremental:
            return self.client.list(
                Pod, gang.meta.namespace,
                selector={c.LABEL_PODGANG_NAME: gang.meta.name})
        pods = self._by_gang.get((gang.meta.namespace, gang.meta.name))
        if not pods:
            return []
        return sorted(pods.values(), key=lambda p: p.meta.name)

    def note_own_writes(self, n: int) -> None:
        self._own_writes += n

    def note_bound(self, pod: Pod) -> None:
        """Account a successfully written bind in place: swap the bound
        clone into the gang index and deduct its chips from its host
        (and every enclosing domain's free total)."""
        gname = pod.meta.labels.get(c.LABEL_PODGANG_NAME)
        if gname:
            self._by_gang[(pod.meta.namespace, gname)][pod.meta.name] = pod
        host = self.host_by_name.get(pod.status.node_name)
        if host is not None:
            self.index.deduct(host, pod.spec.tpu_chips)

    def refresh_if_moved(self) -> None:
        """Keep the in-place-mutated snapshot iff nothing but the
        scheduler's own (counted) writes advanced the store's resource
        version; rebuild otherwise. Non-incremental mode rebuilds
        unconditionally — the pre-snapshot behavior."""
        if (not self.incremental or self.rv < 0
                or not hasattr(self.client, "current_rv")
                or self.client.current_rv() != self.rv + self._own_writes):
            self._build()
            self.rebuilds += 1


def _schedulable(pod: Pod) -> bool:
    return (not pod.spec.scheduling_gates
            and not pod.status.node_name
            and pod.meta.deletion_timestamp is None
            and pod.status.phase.value == "Pending")


class _PlacementLoop:
    """Shared scheduling loop thread driving one backend's place() pass."""

    def __init__(self, name: str, client: Client, tick: float, place) -> None:
        self.name = name
        self.client = client
        self.tick = tick
        self.place = place
        self.log = get_logger(f"scheduler.{name}")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._pump_thread: threading.Thread | None = None
        self._wake = threading.Event()

    def start(self) -> None:
        watcher = self.client.watch(["Pod", "PodGang", "Node"])

        def pump():
            while not self._stop.is_set():
                if watcher.poll(0.2) is None:
                    continue
                # Drain the backlog: N queued events are one wake, not N
                # passes (each pass is O(pods) — per-event passes would be
                # quadratic during large binds).
                while watcher.poll(0) is not None:
                    pass
                self._wake.set()

        self._pump_thread = threading.Thread(
            target=pump, name=f"sched-{self.name}-watch", daemon=True)
        self._pump_thread.start()
        self._thread = threading.Thread(target=self._run,
                                        name=f"sched-{self.name}", daemon=True)
        self._thread.start()

    def request_stop(self) -> None:
        """Signal-only phase of the manager's two-phase shutdown."""
        self._stop.set()
        self._wake.set()

    def stop(self) -> None:
        self.request_stop()
        # A placement pass finishing after stop() binds pods into a
        # store mid-teardown (grovelint thread-join-in-stop). The pump
        # polls at 0.2s, the loop wakes on _wake: both exit promptly.
        for t in (self._thread, getattr(self, "_pump_thread", None)):
            if t is not None:
                t.join(timeout=2.0)
        self._thread = None
        self._pump_thread = None

    def pause(self) -> None:
        """Leadership parking (grove_tpu/ha): a demoted replica's binds
        would be fenced; parking the pass also keeps its placement
        snapshot from fighting the real leader's."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False
        self._wake.set()    # immediate pass: promotion wants placements

    def _run(self) -> None:
        # Writer attribution for store write telemetry: the loop thread
        # is the scheduler's only writer (binds, diagnosis status), so
        # one context set covers every pass.
        from grove_tpu.store import writeobs
        writeobs.set_writer(f"scheduler.{self.name}")
        while not self._stop.is_set():
            self._wake.wait(self.tick)
            self._wake.clear()
            if getattr(self, "_paused", False):
                continue
            try:
                self.place()
            except ConflictError:
                self._wake.set()   # stale write; retry promptly
            except Exception:      # noqa: BLE001 - loop survival barrier
                self.log.exception("placement pass panicked")


class GangBackend:
    """Native TPU gang scheduler."""

    name = "gang"

    def __init__(self) -> None:
        self.client: Client | None = None
        self.namespace = None  # None = every namespace
        self.log = get_logger("scheduler.gang")
        self._loop: _PlacementLoop | None = None
        self._level_labels = dict(DEFAULT_LEVEL_LABELS)

    # ---- TopologyAware interface (reference types.go:59-93) ----

    def sync_topology(self, topology) -> None:
        """Adopt a ClusterTopology's level hierarchy (auto-managed mode)."""
        self._level_labels = {lvl.domain: lvl.node_label
                              for lvl in topology.spec.levels}
        self.log.info("topology synced: %s", list(self._level_labels))

    def check_topology_drift(self, topology) -> bool:
        """True when the backend's live view differs from the CT
        (externally-managed mode: report, don't overwrite)."""
        return self._level_labels != {lvl.domain: lvl.node_label
                                      for lvl in topology.spec.levels}

    # ---- Backend interface ----

    def init(self, client: Client, options: dict[str, str]) -> None:
        self.client = client
        tick = float(options.get("tick_seconds", "0.2"))
        self._loop = _PlacementLoop("gang", client, tick, self._place_pass)
        from grove_tpu.native.loader import prewarm
        from grove_tpu.runtime.events import EventRecorder
        prewarm()  # compile the native core off the placement hot path
        self.recorder = EventRecorder(client, "gang-scheduler")

    def prepare_pod(self, pod: Pod, gang_name: str) -> None:
        pod.spec.scheduler_name = self.name
        pod.meta.labels[c.LABEL_PODGANG_NAME] = gang_name

    def sync_podgang(self, gang: PodGang) -> None:
        # Native backend: the placement loop consumes PodGangs directly;
        # nothing to translate (the reference KAI backend's posture,
        # kai/backend.go:33).
        return

    def validate_pcs(self, pcs: PodCliqueSet) -> list[str]:
        return []

    def runnable(self) -> Optional[_PlacementLoop]:
        return self._loop

    # ---- placement ----

    def _place_pass(self) -> None:
        client = self.client
        assert client is not None
        t0 = time.perf_counter()
        snap = PlacementSnapshot(client, self.namespace, self._level_labels)
        gangs = client.list(PodGang, self.namespace)
        snap.index_gangs(gangs)
        scheduled_by_name = {
            (g.meta.namespace, g.meta.name):
                is_condition_true(g.status.conditions, c.COND_SCHEDULED)
            for g in gangs}
        # Priority first, then base gangs before scaled, then creation
        # time (stable).
        gangs.sort(key=lambda g: (-g.spec.priority, bool(g.spec.base_gang),
                                  g.meta.creation_timestamp))
        try:
            for gang in gangs:
                if gang.spec.scheduler_name not in ("", self.name):
                    continue
                if gang.spec.base_gang and not scheduled_by_name.get(
                        (gang.meta.namespace, gang.spec.base_gang), False):
                    continue  # scaled capacity never blocks/preempts base
                placed, preempted = self._sync_gang(gang, snap)
                if preempted:
                    # Stop the pass: freed capacity must go to the
                    # preemptor on the next pass (which re-sorts by
                    # priority), not to a lower-priority gang later in
                    # THIS pass.
                    break
                if placed:
                    # The bind already mutated the snapshot in place;
                    # a full rebuild happens only when outside writers
                    # moved the store past our own counted writes.
                    snap.refresh_if_moved()
        finally:
            from grove_tpu.runtime.metrics import GLOBAL_METRICS
            GLOBAL_METRICS.observe("grove_sched_place_pass_seconds",
                                   time.perf_counter() - t0, backend="gang")
            # Object-state gauges: currently-unschedulable gangs per
            # diagnosis reason (kube-state-metrics style; reasons that
            # drained are zeroed so alerts clear).
            reasons: dict[str, int] = {}
            for g in gangs:
                d = g.status.last_diagnosis
                if d is not None and d.reason:
                    reasons[d.reason] = reasons.get(d.reason, 0) + 1
            GLOBAL_METRICS.set_gauge_family(
                "grove_gang_unschedulable",
                [({"reason": r}, n) for r, n in reasons.items()])
            if snap.rebuilds and snap.incremental:
                # Legacy mode rebuilds unconditionally — counting those
                # would attribute phantom outside writers.
                GLOBAL_METRICS.inc("grove_sched_snapshot_rebuilds_total",
                                   snap.rebuilds, backend="gang")

    def _gang_pods(self, gang: PodGang,
                   snap: PlacementSnapshot) -> tuple[list[Pod], int, int]:
        """(existing pods of the gang, total expected, min required)."""
        pods = snap.gang_pods(gang)
        by_name = {p.meta.name: p for p in pods}
        existing: list[Pod] = []
        expected = 0
        min_required = 0
        for group in gang.spec.groups:
            expected += len(group.pod_names)
            min_required += group.min_replicas
            for pn in group.pod_names:
                if pn in by_name:
                    existing.append(by_name[pn])
        return existing, expected, min_required

    def _sync_gang(self, gang: PodGang,
                   snap: PlacementSnapshot) -> tuple[bool, bool]:
        """Returns (placed_any, preempted)."""
        hosts = snap.hosts
        existing, expected, min_required = self._gang_pods(gang, snap)
        initialized = expected > 0 and len(existing) == expected

        bindable = [p for p in existing if _schedulable(p)]
        already_bound = [p for p in existing if p.status.node_name]
        gated = [p for p in existing if p.spec.scheduling_gates]

        # Group-level min check on *bindable* pods — and never start the
        # gang while some of its pods are still gated (gate removal is
        # per-pod; placing the early-ungated subset would split the gang).
        bindable_names = {p.meta.name for p in bindable}
        group_ok = (expected > 0 and not gated and all(
            sum(1 for pn in grp.pod_names if pn in bindable_names)
            >= grp.min_replicas
            for grp in gang.spec.groups))

        placed_any = False
        preempted = False
        diag = None
        trace_id = trace_id_of(gang)

        # Reservation-aware placement: a gang holding a bound
        # SliceReservation (defrag migration target, roll-safe slot
        # hold) is constrained to — and admitted onto — the reserved
        # hosts; resolved once per gang, only when the annotation is
        # present (zero cost on the common path).
        hold = self._gang_hold(gang) if bindable else ("", "")

        if not already_bound and group_ok and bindable:
            # First placement: gang-atomic plan over all present pods.
            # The span covers plan + preempt + bind — the
            # scheduler-placement phase of the gang's lifecycle trace
            # (steady-state passes with nothing bindable record none).
            with GLOBAL_TRACER.span(
                    "sched.place", trace_id=trace_id or None,
                    attrs={"gang": gang.meta.name,
                           "pods": len(bindable)}) as span:
                placed_any, preempted, diag = self._place_initial(
                    gang, snap, bindable, span, hold)
        elif already_bound and bindable:
            # Stragglers (scale-up within the gang, or pods re-created
            # after a partial bind): co-locate with their siblings,
            # decrementing the capacity view after each bind. Required
            # packs (gang-level AND group-level) are hard constraints —
            # better an unschedulable pod than a gang whose ICI
            # collectives can never re-form.
            with GLOBAL_TRACER.span(
                    "sched.place", trace_id=trace_id or None,
                    attrs={"gang": gang.meta.name, "straggler": "true",
                           "pods": len(bindable)}):
                bound_domains = self._bound_domains(gang, existing,
                                                    snap.hosts)
                unplaced: list[tuple[Pod, list[HostView]]] = []
                for p in bindable:
                    pool = self._straggler_pool(gang, p, snap,
                                                bound_domains)
                    host = plan_single(
                        PodRequest(p.meta.name, p.spec.tpu_chips,
                                   self._hold_selector(p, hold)),
                        pool, prefer_slice=gang.status.assigned_slice)
                    if host is not None:
                        self._bind([p], {p.meta.name: host}, snap)
                        placed_any = True
                    else:
                        unplaced.append((p, pool))
                if unplaced and explain_enabled():
                    topo = gang.spec.topology
                    lvl = (topo.pack_level if topo else "slice") or "slice"
                    anchor = ""
                    if bound_domains:
                        anchor = next(
                            iter(bound_domains.values())).get(lvl, "")
                    diag = build_straggler_diagnosis(
                        gang, unplaced, lvl,
                        anchor or gang.status.assigned_slice, snap=snap)

        if diag is not None:
            gang.status.last_diagnosis = merge_diagnosis(
                gang.status.last_diagnosis, diag)
        self._update_status(gang, initialized, placed_any, snap)
        return placed_any, preempted

    def _place_initial(self, gang: PodGang, snap: PlacementSnapshot,
                       bindable: list[Pod], span,
                       hold: tuple[str, str] = ("", "")
                       ) -> tuple[bool, bool, object]:
        """First gang-atomic placement (plan → preempt → min-floor
        fallback → bind). Returns (placed_any, preempted, diagnosis) —
        diagnosis is a PlacementDiagnosis when the gang stayed fully
        unplaced and explain is enabled, else None. ``hold`` is the
        gang's bound reservation (name, slice): the injected selector
        both admits the gang onto the fenced hosts and pins it there,
        so a migrating gang relands on its reserved target instead of
        squatting the capacity defrag just freed for someone else."""
        hosts = snap.hosts
        placed_any = False
        preempted = False
        topo = gang.spec.topology
        pack_level = topo.pack_level if topo else "slice"
        required = topo.required if topo else True
        spread = self._spread_penalties(gang, snap)
        hold_slice = hold[1]

        def req(p: Pod) -> PodRequest:
            return PodRequest(p.meta.name, p.spec.tpu_chips,
                              self._hold_selector(p, hold))

        grouped = any(grp.topology is not None and grp.topology.pack_level
                      for grp in gang.spec.groups)

        def make_plan_fn(pods: list[Pod]):
            if grouped:
                # Per-group constraints: hierarchical planning (each
                # constrained group packed into its own sub-domain).
                by_pod = {p.meta.name: p for p in pods}
                greqs = []
                grouped_names: set[str] = set()
                for grp in gang.spec.groups:
                    pods_in = [by_pod[n] for n in grp.pod_names
                               if n in by_pod]
                    grouped_names.update(p.meta.name for p in pods_in)
                    greqs.append(GroupRequest(
                        [req(p) for p in pods_in],
                        grp.topology.pack_level if grp.topology else "",
                        grp.topology.required if grp.topology else True))
                stray = [req(p) for p in pods
                         if p.meta.name not in grouped_names]
                if stray:
                    greqs.append(GroupRequest(stray))
                return lambda hv, idx=None: plan_gang_grouped(
                    greqs, hv, pack_level=pack_level, required=required,
                    prefer_slice=hold_slice or self._reuse_slice(gang),
                    spread_penalty=spread, domain_index=idx)
            requests = [req(p) for p in pods]
            return lambda hv, idx=None: plan_gang(
                requests, hv, pack_level=pack_level, required=required,
                prefer_slice=hold_slice or self._reuse_slice(gang),
                spread_penalty=spread, domain_index=idx)

        plan_fn = make_plan_fn(bindable)
        to_bind = bindable
        diag = None
        pre_out: PreemptionDiagnosis | None = None
        plan = plan_fn(hosts, snap.index)
        if plan is None:
            preempted, pre_out = self._try_preempt_for(gang, plan_fn,
                                                       hosts)
        if plan is None and not preempted:
            if pre_out is not None and \
                    pre_out.verdict == "victims-insufficient":
                # The silent preemption give-up was exactly the on-call
                # blind spot: surface the victim-count shortfall as its
                # own Warning (the generic GangUnschedulable still
                # follows below if nothing else seats the gang).
                snap.note_own_writes(self.recorder.event(
                    gang, "Warning", "PreemptionRejected",
                    f"preemption rejected: {pre_out.victims_considered} "
                    f"elastic victim gang(s) holding "
                    f"{pre_out.victim_chips} chips cannot seat "
                    f"{len(bindable)} pods "
                    f"({sum(p.spec.tpu_chips for p in bindable)} chips); "
                    f"{pre_out.detail}"))
            # Min-floor fallback (reference GS5 semantics), tried
            # only when preemption cannot seat the FULL gang: start
            # with min_replicas per group; surplus pods stay pending
            # and join via the straggler path when capacity appears.
            # Candidate domains are restricted to those whose TOTAL
            # capacity could hold the full gang — a required pack
            # anchors stragglers to the floor's domain, and binding
            # into an undersized one would cap the gang forever.
            floor = self._floor_subset(gang, bindable)
            if floor is not None and len(floor) < len(bindable):
                full_hosts = self._full_headroom_hosts(
                    gang, bindable, snap)
                floor_plan = make_plan_fn(floor)(full_hosts)
                if floor_plan is not None:
                    plan, to_bind = floor_plan, floor
        if plan is not None:
            self._bind(to_bind, plan.assignments, snap)
            gang.status.assigned_slice = plan.slice_name
            gang.status.placement_score = plan.score
            placed_any = True
            span.set_attr("slice", plan.slice_name or "multi-domain")
            from grove_tpu.runtime.metrics import GLOBAL_METRICS
            GLOBAL_METRICS.inc("grove_gang_placements_total")
            snap.note_own_writes(self.recorder.event(
                gang, "Normal", "GangPlaced",
                f"{len(to_bind)} pods onto "
                f"{plan.slice_name or 'multiple domains'} "
                f"(score {plan.score:.2f})"
                + (f"; {len(bindable) - len(to_bind)} surplus pending"
                   if len(to_bind) < len(bindable) else "")))
        else:
            # Preemption was already attempted above (one victim per
            # pass); nothing fit and no floor was possible.
            span.set_error("unschedulable" if not preempted
                           else "preempting")
            if not preempted and explain_enabled():
                # Failed-attempt-only cost: diagnose against the pass
                # snapshot (bounded to the top-K candidate domains).
                diag = build_gang_diagnosis(
                    gang, [req(p) for p in bindable], snap,
                    (pack_level or "slice"), required, spread, pre_out)
            msg = (f"no {pack_level or 'slice'} domain fits "
                   f"{len(bindable)} pods "
                   f"({sum(p.spec.tpu_chips for p in bindable)} chips)")
            if diag is not None:
                msg += f" [{diag.reason}]"
            snap.note_own_writes(self.recorder.event(
                gang, "Warning", "GangUnschedulable", msg))
        return placed_any, preempted, diag

    def _floor_subset(self, gang: PodGang,
                      bindable: list[Pod]) -> list[Pod] | None:
        """Per-group min_replicas subset of ``bindable`` (lowest pod
        INDICES first — a JAX process group expects the contiguous low
        worker ids, coordinator at rank 0); pods outside any group are
        kept whole. None when some group cannot even meet its floor."""
        def pod_index(p: Pod) -> int:
            try:
                return namegen.pod_index_from_name(p.meta.name)
            except ValueError:
                return 1 << 30
        by_pod = {p.meta.name: p for p in bindable}
        subset: list[Pod] = []
        claimed: set[str] = set()
        for grp in gang.spec.groups:
            pods_in = [by_pod[n] for n in grp.pod_names if n in by_pod]
            if len(pods_in) < grp.min_replicas:
                return None
            pods_in.sort(key=pod_index)
            subset.extend(pods_in[:grp.min_replicas])
            claimed.update(grp.pod_names)
        subset.extend(p for p in bindable if p.meta.name not in claimed)
        return subset

    def _full_headroom_hosts(self, gang: PodGang, bindable: list[Pod],
                             snap: PlacementSnapshot) -> list[HostView]:
        """Hosts whose pack-level domain could hold the FULL gang by
        total capacity. Only meaningful under a required pack (which
        anchors later stragglers to the floor's domain); otherwise all
        hosts qualify."""
        hosts = snap.hosts
        topo = gang.spec.topology
        if topo is None or not topo.required or not topo.pack_level:
            return hosts
        level_label = self._level_labels.get(topo.pack_level)
        if level_label is None:
            return hosts
        need = sum(p.spec.tpu_chips for p in bindable)
        # Physical capacity: ALL nodes count, including cordoned or
        # not-ready ones — they are temporarily out, not absent, and the
        # question is whether the domain could EVER hold the full gang.
        # The snapshot's raw node list carries exactly that view.
        total_by_domain: dict[str, int] = defaultdict(int)
        for node in snap.nodes:
            total_by_domain[node.meta.labels.get(level_label, "")] += \
                node.status.allocatable_chips
        return [h for h in hosts
                if total_by_domain[h.domains.get(topo.pack_level, "")]
                >= need]

    def _try_preempt_for(self, gang: PodGang, plan_fn,
                         hosts: list[HostView]
                         ) -> tuple[bool, PreemptionDiagnosis]:
        """Free capacity for a starved BASE gang by evicting one scaled
        (elastic) gang of equal-or-lower priority. Returns
        (preempted, outcome) — the outcome records WHY preemption was
        rejected (not-eligible / no-victims / victims-insufficient) for
        the placement diagnosis and the PreemptionRejected event.

        Elastic capacity is best-effort by definition — the base-gang
        guarantee ('scaled capacity never starves the base', reference
        syncflow.go:387 gating) extends across PodCliqueSets here.
        ``plan_fn`` is the exact planner the gang failed with (flat or
        per-group): eviction happens only when some victim's reclaimed
        capacity makes that very plan feasible — the cheapest such victim
        by (priority, chips). The victim's pods are deleted; its
        PodClique recreates them gated and the gang re-queues behind the
        preemptor. One victim per pass keeps eviction minimal.
        """
        if gang.spec.base_gang:
            # only base gangs preempt
            return False, PreemptionDiagnosis(
                verdict="not-eligible",
                detail="scaled (elastic) gangs never preempt")
        client = self.client
        victims = []
        # Victims cluster-wide: capacity is one pool, so preemption must
        # see elastic gangs in every namespace.
        for other in client.list(PodGang, None):
            if not other.spec.base_gang:
                continue  # never evict another base gang
            if other.spec.priority > gang.spec.priority:
                continue
            # Only capacity the victim actually holds (matches the
            # used-chips predicate of build_host_views).
            pods = [p for p in client.list(
                Pod, other.meta.namespace,
                selector={c.LABEL_PODGANG_NAME: other.meta.name})
                if p.status.node_name
                and p.meta.deletion_timestamp is None
                and p.status.phase.value in ("Pending", "Running")]
            if not pods:
                continue
            victims.append((sum(p.spec.tpu_chips for p in pods), other, pods))
        if not victims:
            return False, PreemptionDiagnosis(
                verdict="no-victims",
                detail="no elastic gang at equal-or-lower priority "
                       "holds capacity")
        total_victim_chips = sum(chips for chips, _, _ in victims)
        insufficient = PreemptionDiagnosis(
            verdict="victims-insufficient",
            victims_considered=len(victims),
            victim_chips=total_victim_chips,
            detail=f"evicting all {len(victims)} elastic gang(s) "
                   f"({total_victim_chips} chips) still cannot seat "
                   "the gang")

        def feasible_with(victim_pods) -> bool:
            reclaim: dict[str, int] = defaultdict(int)
            for p in victim_pods:
                reclaim[p.status.node_name] += p.spec.tpu_chips
            potential = [
                HostView(h.name, h.free_chips + reclaim.get(h.name, 0),
                         dict(h.domains), dict(h.labels)) for h in hosts]
            return plan_fn(potential) is not None

        # Cheapest single victim whose eviction alone makes the plan work.
        viable = [(chips, v, pods) for chips, v, pods in victims
                  if feasible_with(pods)]
        if not viable:
            # Multi-victim scenarios: evict only when everything together
            # would work, and then only a victim intersecting the plan's
            # chosen hosts (never an irrelevant one).
            all_pods = [p for _, _, pods in victims for p in pods]
            if not feasible_with(all_pods):
                return False, insufficient
            reclaim_all: dict[str, int] = defaultdict(int)
            for p in all_pods:
                reclaim_all[p.status.node_name] += p.spec.tpu_chips
            potential = [
                HostView(h.name, h.free_chips + reclaim_all.get(h.name, 0),
                         dict(h.domains), dict(h.labels)) for h in hosts]
            plan = plan_fn(potential)
            used_hosts = set(plan.assignments.values())
            viable = [(chips, v, pods) for chips, v, pods in victims
                      if any(p.status.node_name in used_hosts for p in pods)]
            if not viable:
                insufficient.detail = (
                    f"{len(victims)} elastic gang(s) hold "
                    f"{total_victim_chips} chips but none intersects "
                    "the feasible plan's hosts")
                return False, insufficient
        _, victim, pods = min(viable, key=lambda v: (v[1].spec.priority, v[0]))
        self.log.info("preempting scaled gang %s (priority %d) for base "
                      "gang %s (priority %d)", victim.meta.name,
                      victim.spec.priority, gang.meta.name,
                      gang.spec.priority)
        self.recorder.event(
            victim, "Warning", "GangPreempted",
            f"evicted for starved base gang {gang.meta.name} "
            f"(priority {gang.spec.priority} >= {victim.spec.priority})")
        for p in pods:
            try:
                client.delete(Pod, p.meta.name, p.meta.namespace)
            except (NotFoundError, ConflictError):
                pass
        return True, PreemptionDiagnosis(
            verdict="preempted", victims_considered=len(victims),
            victim_chips=sum(p.spec.tpu_chips for p in pods),
            detail=f"evicted {victim.meta.name}")

    def _bound_domains(self, gang: PodGang, existing: list[Pod],
                       hosts: list[HostView]) -> dict[str, dict[str, str]]:
        """Per group: the domain (at every level) of its bound pods —
        the anchor stragglers must rejoin. {group_name: {level: domain}}."""
        host_by_name = {h.name: h for h in hosts}
        out: dict[str, dict[str, str]] = {}
        pod_by_name = {p.meta.name: p for p in existing}
        for grp in gang.spec.groups:
            for pn in grp.pod_names:
                p = pod_by_name.get(pn)
                if p is None or not p.status.node_name:
                    continue
                h = host_by_name.get(p.status.node_name)
                if h is not None:
                    out[grp.name] = dict(h.domains)
                    break
        return out

    def _straggler_pool(self, gang: PodGang, pod: Pod,
                        snap: PlacementSnapshot,
                        bound_domains: dict[str, dict[str, str]]
                        ) -> list[HostView]:
        """Hosts a late pod may bind to: every *required* pack constraint
        (gang-level and its group's) restricts to the domain its bound
        siblings occupy. The first constraint resolves through the
        snapshot's domain index (no full-fleet scan per straggler)."""
        constraints: list[tuple[str, str]] = []  # (level, domain value)
        gang_topo = gang.spec.topology
        gang_level = gang_topo.pack_level if gang_topo else "slice"
        gang_required = gang_topo.required if gang_topo else True
        my_group = next((g for g in gang.spec.groups
                         if pod.meta.name in g.pod_names), None)
        anchor = bound_domains.get(my_group.name) if my_group else None
        if anchor is None and bound_domains:
            anchor = next(iter(bound_domains.values()))
        if anchor:
            if gang_required and gang_level:
                constraints.append((gang_level, anchor.get(gang_level, "")))
            if (my_group is not None and my_group.topology is not None
                    and my_group.topology.pack_level
                    and my_group.topology.required
                    and my_group.name in bound_domains):
                lvl = my_group.topology.pack_level
                constraints.append(
                    (lvl, bound_domains[my_group.name].get(lvl, "")))
        pool = snap.hosts
        first = True
        for level, value in constraints:
            if not value:
                continue
            if first and snap.index.domains(level) is not None:
                pool = snap.index.hosts_in(level, value)
            else:
                pool = [h for h in pool if h.domains.get(level) == value]
            first = False
        return pool

    def _mirror_disruption(self, gang: PodGang):
        """Mirror the disruption-notice annotation into
        ``status.disruption`` and return the DisruptionTarget condition
        to set (None when there is no notice and no stale True
        condition to clear). Mirror-only: posting/acking/clearing live
        in disruption/contract.py."""
        from grove_tpu.disruption.contract import barrier_state, notice_of
        notice = notice_of(gang)
        gang.status.disruption = notice
        if notice is not None:
            state = barrier_state(notice)
            return Condition(
                type=c.COND_DISRUPTION_TARGET, status="True",
                reason=notice.reason,
                message=f"barrier {state} (notice {notice.id}"
                        + (f", evicted" if notice.evicted_at else "") + ")")
        if is_condition_true(gang.status.conditions,
                             c.COND_DISRUPTION_TARGET):
            return Condition(type=c.COND_DISRUPTION_TARGET,
                             status="False", reason="NoticeCleared")
        return None

    def _gang_hold(self, gang: PodGang) -> tuple[str, str]:
        """Resolve the gang's reuse-reservation-ref annotation to a
        BOUND SliceReservation: (name, first bound slice). ("", "")
        when absent, missing, or not yet bound — an unbound hold never
        constrains placement (a lost target must not wedge the gang)."""
        ref = gang.meta.annotations.get(c.ANNOTATION_RESERVATION_REF, "")
        if not ref:
            return "", ""
        from grove_tpu.api import SliceReservation
        from grove_tpu.api.reservation import ReservationPhase
        try:
            rsv = self.client.get(SliceReservation, ref,
                                  gang.meta.namespace)
        except NotFoundError:
            return "", ""
        if rsv.status.phase != ReservationPhase.BOUND \
                or not rsv.status.bound_slices:
            return "", ""
        return ref, rsv.status.bound_slices[0]

    @staticmethod
    def _hold_selector(pod: Pod, hold: tuple[str, str]) -> dict[str, str]:
        """The pod's node selector with the gang's bound hold injected:
        reserved hosts are fenced (placement._selector_matches), so the
        selector is what ADMITS the gang onto its own hold — and pins it
        there. A clique that already selects a PCS-level reservation is
        left alone (two reservation keys can never both match)."""
        sel = dict(pod.spec.node_selector)
        if hold[0] and c.LABEL_RESERVATION not in sel:
            sel[c.LABEL_RESERVATION] = hold[0]
        return sel

    def _reuse_slice(self, gang: PodGang) -> str:
        """Resolve the placement-reuse hint to a slice name: an explicit
        preferred-slice annotation (rolling updates stamp the replaced
        gang's slice there) or a live gang named by reuse_reservation_of."""
        hint = gang.meta.annotations.get(f"{c.DOMAIN}/preferred-slice", "")
        if hint:
            return hint
        if not gang.spec.reuse_reservation_of:
            return ""
        try:
            old = self.client.get(PodGang, gang.spec.reuse_reservation_of,
                                  gang.meta.namespace)
            return old.status.assigned_slice
        except NotFoundError:
            return ""

    def _spread_penalties(self, gang: PodGang,
                          snap: PlacementSnapshot) -> dict[str, float]:
        """Penalise slices already hosting sibling gangs of the same PCS
        (DCN multislice spread of PCS replicas). Siblings come from the
        pass's gang index (one scan per pass, not one selector list per
        gang); in-pass placements are visible because the index holds
        the very objects the pass mutates."""
        pcs = gang.meta.labels.get(c.LABEL_PCS_NAME, "")
        if not pcs:
            return {}
        penalties: dict[str, float] = defaultdict(float)
        for other in snap.pcs_siblings(gang.meta.namespace, pcs):
            if other.meta.name != gang.meta.name and other.status.assigned_slice:
                # Must dominate bin-pack tightness (<= 1.0) so multislice
                # replicas spread before they pack.
                penalties[other.status.assigned_slice] += 2.0
        return dict(penalties)

    def _bind(self, pods: list[Pod], assignment: dict[str, str],
              snap: PlacementSnapshot) -> None:
        trace_id = trace_id_of(pods[0]) if pods else ""
        with GLOBAL_TRACER.span("sched.bind", trace_id=trace_id or None,
                                attrs={"pods": len(pods)}):
            self._bind_traced(pods, assignment, snap)

    def _bind_traced(self, pods: list[Pod], assignment: dict[str, str],
                     snap: PlacementSnapshot) -> None:
        to_write = []
        for pod in pods:
            host = assignment.get(pod.meta.name)
            if host is None:
                continue
            # Snapshot pods are SHARED read-only objects — clone before
            # stamping the binding (the write payload is ours alone).
            bound = clone(pod) if snap.incremental else pod
            bound.status.node_name = host
            to_write.append(bound)
        # One batched store transaction: per-pod locking would serialise a
        # large gang bind against every reader. Individual failures (pod
        # vanished / changed under us in a scale-in race) are skipped; the
        # next pass replans from live state — aborting would strand the
        # rest of the gang mid-bind.
        for pod, err in zip(to_write,
                            self.client.update_status_many(to_write)):
            if err is not None:
                self.log.debug("bind %s skipped: %s", pod.meta.name, err)
                continue
            snap.note_own_writes(1)
            snap.note_bound(pod)

    def _update_status(self, gang: PodGang, initialized: bool,
                       placed_now: bool, snap: PlacementSnapshot) -> None:
        client = self.client
        # Mirror the reuse-reservation-ref annotation (written by the
        # defrag executor / rolling-update hold path) into status — the
        # scheduler is the single PodGang status writer, so the mirror
        # rides every status write instead of adding a second writer.
        gang.status.reuse_reservation_ref = gang.meta.annotations.get(
            c.ANNOTATION_RESERVATION_REF, "")
        # Same single-writer mirror for the disruption contract: the
        # live notice (disruption/contract.py annotation) lands in
        # status.disruption + a DisruptionTarget condition carrying the
        # barrier verdict, so every read surface sees the planned
        # eviction without a second status writer.
        disruption_cond = self._mirror_disruption(gang)
        existing, expected, _ = self._gang_pods(gang, snap)
        bound = sum(1 for p in existing if p.status.node_name)
        ready = sum(1 for p in existing
                    if is_condition_true(p.status.conditions, c.COND_READY))
        scheduled = expected > 0 and bound >= sum(
            g.min_replicas for g in gang.spec.groups)
        all_ready = bool(expected) and ready == expected
        # Lifecycle milestones for the SLO histograms: recorded on the
        # condition's first flip (the tracer dedups repeats, so the
        # prior-state checks only save the call at steady state).
        trace_id = trace_id_of(gang)
        if trace_id:
            subject = f"{gang.meta.namespace}/{gang.meta.name}"
            if scheduled and not is_condition_true(gang.status.conditions,
                                                   c.COND_SCHEDULED):
                GLOBAL_TRACER.milestone(trace_id, subject, "scheduled")
            if all_ready and not is_condition_true(gang.status.conditions,
                                                   c.COND_READY):
                GLOBAL_TRACER.milestone(trace_id, subject, "ready")
        conds = gang.status.conditions
        conds = set_condition(conds, Condition(
            type=c.COND_INITIALIZED, status="True" if initialized else "False",
            reason="AllPodsCreated" if initialized else "AwaitingPods"))
        conds = set_condition(conds, Condition(
            type=c.COND_SCHEDULED, status="True" if scheduled else "False",
            reason="GangPlaced" if scheduled else "AwaitingPlacement"))
        conds = set_condition(conds, Condition(
            type=c.COND_READY,
            status="True" if all_ready else "False",
            reason=f"{ready}/{expected} ready"))
        if disruption_cond is not None:
            conds = set_condition(conds, disruption_cond)
        # Placement explainability: mirror the diagnosis headline into
        # an Unschedulable condition; on schedule, observe how long the
        # gang sat pending and clear the diagnosis (it answered its
        # question). An unchanged diagnosis re-sets an identical
        # condition — a suppressed no-op write.
        diag = gang.status.last_diagnosis
        if diag is not None:
            # A straggler diagnosis coexists with Scheduled=True (the
            # floor is placed; the surplus is stuck): it clears only
            # when every expected pod is bound, not at the min floor.
            resolved = scheduled and (diag.reason != "StragglerUnplaced"
                                      or bound >= expected)
            if resolved:
                from grove_tpu.runtime.metrics import GLOBAL_METRICS
                GLOBAL_METRICS.observe(
                    "grove_gang_pending_seconds",
                    max(0.0, time.time() - diag.first_failure_time))
                gang.status.last_diagnosis = None
                conds = set_condition(conds, Condition(
                    type=c.COND_UNSCHEDULABLE, status="False",
                    reason="Scheduled"))
            else:
                conds = set_condition(conds, Condition(
                    type=c.COND_UNSCHEDULABLE, status="True",
                    reason=diag.reason, message=diag.message[:240]))
        gang.status.conditions = conds
        if all_ready:
            gang.status.phase = PodGangPhase.RUNNING
        elif scheduled:
            gang.status.phase = PodGangPhase.STARTING
        else:
            gang.status.phase = PodGangPhase.PENDING
        def write(g: PodGang) -> None:
            updated = client.update_status(g)  # no-op writes suppressed
            if updated.meta.resource_version != g.meta.resource_version:
                snap.note_own_writes(1)

        try:
            write(gang)
        except ConflictError:
            # The podgang controller races this write (our own bind
            # events wake it mid-pass). Reapply on a fresh read so
            # Scheduled/assigned_slice land THIS pass instead of
            # waiting out a full extra pass; a second conflict defers
            # to the next pass as before.
            try:
                fresh = client.get(PodGang, gang.meta.name,
                                   gang.meta.namespace)
                fresh.status.conditions = gang.status.conditions
                fresh.status.phase = gang.status.phase
                fresh.status.assigned_slice = gang.status.assigned_slice
                fresh.status.placement_score = gang.status.placement_score
                fresh.status.last_diagnosis = gang.status.last_diagnosis
                # Re-mirror from the FRESH annotations: the conflicting
                # writer may have been the hold path (or the disruption
                # contract) itself.
                fresh.status.reuse_reservation_ref = \
                    fresh.meta.annotations.get(
                        c.ANNOTATION_RESERVATION_REF, "")
                from grove_tpu.disruption.contract import notice_of
                fresh.status.disruption = notice_of(fresh)
                write(fresh)
            except (ConflictError, NotFoundError):
                pass  # next pass recomputes from live state
        except NotFoundError:
            pass  # gang deleted under us; nothing to record


class SimpleBackend:
    """Non-gang first-fit placement (the kube-scheduler role)."""

    name = "simple"

    def __init__(self) -> None:
        self.client: Client | None = None
        self.namespace = None  # None = every namespace
        self._loop: _PlacementLoop | None = None

    def init(self, client: Client, options: dict[str, str]) -> None:
        self.client = client
        tick = float(options.get("tick_seconds", "0.2"))
        self._loop = _PlacementLoop("simple", client, tick, self._place_pass)

    def prepare_pod(self, pod: Pod, gang_name: str) -> None:
        pod.spec.scheduler_name = self.name
        pod.meta.labels[c.LABEL_PODGANG_NAME] = gang_name

    def sync_podgang(self, gang: PodGang) -> None:
        return

    def validate_pcs(self, pcs: PodCliqueSet) -> list[str]:
        return []

    def runnable(self) -> Optional[_PlacementLoop]:
        return self._loop

    def _place_pass(self) -> None:
        client = self.client
        t0 = time.perf_counter()
        hosts = build_host_views(client, self.namespace)
        by_name = {h.name: h for h in hosts}
        for pod in client.list(Pod, self.namespace):
            if pod.spec.scheduler_name not in ("", self.name):
                continue
            if not _schedulable(pod):
                continue
            host = plan_single(
                PodRequest(pod.meta.name, pod.spec.tpu_chips,
                           dict(pod.spec.node_selector)), hosts)
            if host is not None:
                pod.status.node_name = host  # grovelint: disable=clone-before-mutate -- the simple backend lists through the DIRECT leader client (store lists clone per call); only the gang backend reads shared snapshots
                client.update_status(pod)
                # In-place deduction replaces the full per-bind re-list
                # (the same accounting the rebuild would arrive at).
                by_name[host].free_chips -= pod.spec.tpu_chips
        from grove_tpu.runtime.metrics import GLOBAL_METRICS
        GLOBAL_METRICS.observe("grove_sched_place_pass_seconds",
                               time.perf_counter() - t0, backend="simple")


class ExternalBackend:
    """Delegate placement to an out-of-process scheduler (lpx role)."""

    name = "external"

    def __init__(self, scheduler_name: str = "external"):
        self.scheduler_name = scheduler_name

    def init(self, client: Client, options: dict[str, str]) -> None:
        self.scheduler_name = options.get("scheduler_name", self.scheduler_name)

    def prepare_pod(self, pod: Pod, gang_name: str) -> None:
        pod.spec.scheduler_name = self.scheduler_name
        pod.meta.labels[c.LABEL_PODGANG_NAME] = gang_name

    def sync_podgang(self, gang: PodGang) -> None:
        return

    def validate_pcs(self, pcs: PodCliqueSet) -> list[str]:
        problems = []
        t = pcs.spec.template
        if t.topology is not None:
            problems.append(
                "external scheduler profile does not support grove topology "
                "constraints (set them in the external scheduler instead)")
        return problems

    def runnable(self) -> None:
        return None
